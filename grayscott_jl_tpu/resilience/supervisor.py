"""Run supervision: classify failures, back off, auto-resume.

The open-loop driver dies on the first async-writer error, NaN blow-up,
preemption, or Mosaic regression — with whatever the checkpoint store
happened to hold. ``supervise(settings)`` closes the loop around a
refactored ``driver.run_once``; it is the preemption-safe-loop shape
shared with long-training stacks (arXiv:2309.10292 §5 runs the same
checkpoint/restart discipline on Frontier; arXiv:2404.02218 argues the
runtime layer, not user code, must absorb these):

* **classify** the failure — ``transient-io`` (an ``AsyncIOError``
  whose original is an OS-level error, or a bare ``OSError``),
  ``preemption`` (:class:`~.faults.PreemptionError`), ``health``
  (:class:`~.health.HealthError` under the ``rollback`` policy),
  ``kernel`` (a Mosaic/Pallas runtime failure), or ``corruption``
  (:class:`~.integrity.CorruptionError` — a CRC or device-checksum
  mismatch; restartable with replica failover, but the SAME corrupt
  step recurring is non-transient and gives up instead of looping).
  Anything else — a config error, a programming bug — re-raises
  immediately: retrying an unclassified failure just burns
  accelerator time.
* **retry** with exponential backoff (base ``GS_RESTART_BACKOFF_S``,
  default 0.5 s, cap 30 s) plus deterministic jitter (crc32 of the
  attempt/kind, not a live RNG — replayable), up to ``GS_MAX_RESTARTS``.
* **auto-resume**: before each retry the latest *durable* checkpoint is
  located (``bplite.BpReader`` exposes only complete steps, so a crash
  mid-checkpoint never resumes from a torn entry) and the settings are
  rewritten to ``restart=true`` pointing at ``checkpoint_output``. No
  checkpoint yet means a from-scratch restart.
* **degrade** ``kernel_language`` Pallas->XLA on a kernel-runtime
  failure, recording the degradation in the ``kernel_selection``
  provenance of the final ``RunStats`` — the run finishes slower
  rather than not at all, and the stats say why.
* **journal** every failure and recovery action as JSONL
  (:class:`FaultJournal`; every line flushed + fsynced, so the journal
  survives SIGKILL mid-event); the completing attempt merges the full
  journal into ``RunStats`` as its ``faults`` section. In multi-process
  runs each rank journals to its own ``.rank<N>``-suffixed file and
  every event carries the rank's ``proc``.

Multi-host runs are supervised for real (PR 5; the old per-process
refusal is gone): on a classified failure the ranks rendezvous
(:mod:`.rendezvous` — coordination-service KV when
``jax.distributed`` is initialized, filesystem otherwise), adopt a
cluster-wide attempt counter (max) and the quorum restart step (the
*minimum* latest-durable-checkpoint across hosts), and restart
together. A :class:`~.faults.GracefulShutdown` (real SIGTERM/SIGINT
preemption) is never restarted in-process — the scheduler wants the
process gone; it exits with :data:`~.faults.EXIT_PREEMPTED` and the
journal's ``graceful_shutdown`` marker makes the *next* supervised
launch auto-resume (:func:`resume_marker`). The hang watchdog's hard
exit leaves the analogous ``hang_exit`` marker.
"""

from __future__ import annotations

import dataclasses
import os
import time
import zlib
from typing import List, Optional

from ..config.env import env_float, env_raw, env_str
from .faults import (
    FaultPlan,
    GracefulShutdown,
    InjectedKernelError,
    PreemptionError,
)
from .health import HealthError
from .watchdog import HangError

__all__ = [
    "FaultJournal",
    "SupervisorContext",
    "classify_failure",
    "latest_durable_checkpoint",
    "restart_backoff",
    "resolve_max_restarts",
    "resume_marker",
    "supervise",
    "supervision_enabled",
]

#: Journal events that mark a run interrupted by an external teardown
#: (graceful preemption exit, watchdog hard exit) — a *resumable* end:
#: when the last journal line is one of these, the next supervised
#: launch restarts from the durable checkpoint without waiting for a
#: fresh failure.
RESUME_MARKERS = ("graceful_shutdown", "hang_exit")

_TRUTHY = {"1", "true", "yes", "on"}
_FALSY = {"0", "false", "no", "off"}


def supervision_enabled(settings=None) -> bool:
    """``GS_SUPERVISE`` env, else the ``supervise`` TOML key."""
    raw = env_raw("GS_SUPERVISE")
    if raw is not None:
        val = raw.strip().lower()
        if val in _TRUTHY:
            return True
        if val in _FALSY:
            return False
        raise ValueError(
            f"GS_SUPERVISE must be a boolean (0/1/true/false), got {raw!r}"
        )
    return bool(getattr(settings, "supervise", False))


def resolve_max_restarts(settings=None) -> int:
    """``GS_MAX_RESTARTS`` env, else the ``max_restarts`` TOML key."""
    raw = os.environ.get("GS_MAX_RESTARTS")
    if raw is not None:
        try:
            n = int(raw)
        except ValueError as e:
            raise ValueError(
                f"GS_MAX_RESTARTS must be an integer, got {raw!r}"
            ) from e
    else:
        n = int(getattr(settings, "max_restarts", 3))
    if n < 0:
        raise ValueError(f"max restarts must be >= 0, got {n}")
    return n


def restart_backoff(attempt: int, kind: str) -> float:
    """Exponential backoff with deterministic jitter.

    ``base * 2**attempt`` capped at 30 s, plus up to 25% jitter derived
    from crc32(attempt:kind) — spread-out restarts without an RNG, so a
    replayed chaos run sleeps the same schedule every time.
    """
    base = env_float("GS_RESTART_BACKOFF_S", 0.5)
    if base < 0:
        raise ValueError(
            f"GS_RESTART_BACKOFF_S must be >= 0, got {base}"
        )
    delay = min(base * (2 ** attempt), 30.0)
    frac = (zlib.crc32(f"{attempt}:{kind}".encode()) % 1000) / 1000.0
    return delay * (1.0 + 0.25 * frac)


class FaultJournal:
    """Append-only fault/recovery event log, mirrored to JSONL.

    Events are plain dicts; ``record`` is called from the driver thread
    (nan/preempt/health/recovery events), from the async writer's
    worker thread (fired io_error injections), and from the watchdog's
    monitor thread (hang events), so the file append is lock-guarded.
    Every appended line is flushed and fsynced before ``record``
    returns: the journal is the recovery breadcrumb a SIGKILLed or
    preempted process leaves behind, and a buffered line that died with
    the process would hand the next launch an inconsistent fault
    history. The journal object outlives run attempts — the completing
    attempt merges ``events`` into ``RunStats``.

    ``process_index`` (set for multi-process runs) is stamped onto
    every event as ``proc`` so a merged cross-rank read attributes each
    fault to the host that saw it.
    """

    def __init__(self, path: Optional[str] = None,
                 process_index: Optional[int] = None):
        import threading

        self.path = path
        self.process_index = process_index
        self.events: List[dict] = []
        self._lock = threading.Lock()

    @classmethod
    def from_env(cls, settings=None) -> "FaultJournal":
        """Journal at ``GS_FAULT_JOURNAL``; default ``<output>.faults.jsonl``
        under supervision, in-memory only otherwise. In multi-process
        runs the path gets a ``.rank<N>`` suffix (mirroring
        ``GS_TPU_STATS``) and events are tagged with the rank."""
        path = env_raw("GS_FAULT_JOURNAL")
        if not path and settings is not None and supervision_enabled(settings):
            path = settings.output + ".faults.jsonl"
        proc = None
        import sys

        if "jax" in sys.modules:  # never force a backend init from here
            import jax

            if jax.process_count() > 1:
                proc = jax.process_index()
                if path:
                    path = f"{path}.rank{proc}"
        return cls(path or None, process_index=proc)

    def record(self, **event) -> dict:
        import json

        event.setdefault("t", round(time.time(), 3))
        if self.process_index is not None:
            event.setdefault("proc", self.process_index)
        with self._lock:
            self.events.append(event)
            if self.path:
                with open(self.path, "a", encoding="utf-8") as f:
                    f.write(json.dumps(event) + "\n")
                    f.flush()
                    os.fsync(f.fileno())
        self._mirror_to_stream(event)
        return event

    @staticmethod
    def _mirror_to_stream(event: dict) -> None:
        """Route the journal event into the unified run event stream
        (``obs/events.py``, ``GS_EVENTS``): the journal's ``event``
        name becomes the stream ``kind``, the failure-taxonomy ``kind``
        rides in attrs as ``fault`` — so injected faults, health trips,
        watchdog expiries (stack dumps included), restart decisions,
        and shutdown markers are all tailable live from one file. The
        stream is best-effort by contract; the fsynced journal above
        stays the durable record."""
        from ..obs import events as obs_events

        stream = obs_events.get_events()
        if not stream.enabled:
            return
        attrs = dict(event)
        kind = attrs.pop("event", None) or attrs.pop("kind", "event")
        fault = attrs.pop("kind", None)
        if fault is not None:
            attrs["fault"] = fault
        attrs.pop("t", None)
        attrs.pop("proc", None)
        stream.emit(kind, phase=attrs.pop("phase", None),
                    step=attrs.pop("step", None), **attrs)


def resume_marker(path: Optional[str]) -> Optional[dict]:
    """The journal's trailing resume marker, or None.

    Reads the JSONL at ``path`` and returns the last event iff it is a
    :data:`RESUME_MARKERS` kind — i.e. the previous launch ended in a
    graceful preemption exit or a watchdog hard exit and nothing has
    resumed it since (any later event, e.g. the resuming launch's own
    ``recovery`` record, clears the marker). Corrupt lines are skipped:
    ``record`` fsyncs whole lines, but a torn tail from a mid-write
    SIGKILL must not block the resume of everything before it.
    """
    import json

    if not path or not os.path.exists(path):
        return None
    last = None
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                last = json.loads(line)
            except json.JSONDecodeError:
                continue
    if isinstance(last, dict) and last.get("event") in RESUME_MARKERS:
        return last
    return None


@dataclasses.dataclass
class SupervisorContext:
    """Per-attempt state the supervisor threads through ``run_once``."""

    plan: FaultPlan
    journal: FaultJournal
    attempt: int = 0
    #: kernel_selection provenance patch after a Pallas->XLA degrade.
    degraded: Optional[dict] = None
    #: The attempt's live RunStats (set by the driver once built): a
    #: failing attempt's phase timings would otherwise die with the
    #: attempt — the supervisor journals them as an ``attempt_phases``
    #: event, so the completing attempt's ``faults`` section attributes
    #: wall time per restart attempt (``scripts/gs_report.py``).
    stats: Optional[object] = None


#: Message fragments that identify a kernel-runtime failure raised by
#: the TPU compiler/runtime stack (vs our injected marker, which
#: carries "Mosaic" too).
_KERNEL_MARKERS = ("mosaic", "pallas")


def classify_failure(exc: BaseException) -> Optional[str]:
    """Map a run failure onto the recovery taxonomy, or None (fatal).

    The classification deliberately whitelists: only failure shapes
    with a known recovery action are retried. ``AsyncIOError`` is
    unwrapped to its original exception (``io/async_writer.py`` tags
    transience there, where the failing write happened).
    """
    from ..io.async_writer import AsyncIOError
    from .integrity import CorruptionError
    from .sdc import SDCError

    if isinstance(exc, SDCError):
        # Compute-path silent corruption caught by the redundant-compute
        # screener (``resilience/sdc.py``): restartable from the last
        # *verified* checkpoint. supervise() owns the escalation —
        # repeated attribution to the SAME device is a deterministic
        # fault, quarantined rather than retried forever.
        return "sdc"
    if isinstance(exc, PreemptionError):
        # GracefulShutdown is a PreemptionError too: same taxonomy slot,
        # but supervise() re-raises it without an in-process restart.
        return "preemption"
    if isinstance(exc, HangError):
        return "hang"
    if isinstance(exc, HealthError):
        # abort policy means abort: only rollback is recoverable.
        return "health" if exc.policy == "rollback" else None
    if isinstance(exc, InjectedKernelError):
        return "kernel"
    if isinstance(exc, CorruptionError):
        # Detected silent corruption (CRC/device-checksum mismatch):
        # restartable — the restore fails over to a healthy replica,
        # or a clean re-snapshot replaces the corrupted boundary. The
        # restart loop itself refuses to spin on the SAME corrupt step
        # twice (supervise() tracks it; repeated corruption of one
        # step is a rotten store, not a transient).
        return "corruption"
    if isinstance(exc, AsyncIOError):
        if isinstance(exc.original, CorruptionError):
            # Unwrap like transience: the corruption was detected on
            # the writer thread (snapshot verify, read-back verify).
            return "corruption"
        return "transient-io" if exc.transient else None
    if isinstance(exc, OSError):
        return "transient-io"
    # Real Mosaic/Pallas runtime failures surface as XLA runtime errors
    # whose type lives in jaxlib; match on the message rather than
    # importing a version-dependent exception type.
    name = type(exc).__name__
    if name in ("XlaRuntimeError", "InternalError"):
        msg = str(exc).lower()
        if any(m in msg for m in _KERNEL_MARKERS):
            return "kernel"
    return None


def _corruption_signature(exc: BaseException):
    """What exactly was corrupt — ``(step, var, file)`` pulled from
    the (possibly async-wrapped) :class:`~.integrity.CorruptionError`.
    The supervisor restarts a corruption ONCE per signature: the first
    occurrence gets the failover/re-snapshot retry, a recurrence of
    the same signature means the data itself is rotten on every
    replica and retrying forever would just burn accelerator time."""
    from ..io.async_writer import AsyncIOError
    from .integrity import CorruptionError

    e = exc.original if isinstance(exc, AsyncIOError) else exc
    if isinstance(e, CorruptionError):
        return (e.step, e.var, e.file)
    return (getattr(exc, "step", None), None, None)


def latest_durable_checkpoint(settings,
                              max_step: Optional[int] = None
                              ) -> Optional[int]:
    """Simulation step of the latest *complete* checkpoint entry, or
    None. Checkpoints are always BP-lite stores
    (``io/checkpoint.py`` pins ``prefer_adios2=False``), and the
    reader's durability validation (``io/bplite.py``) already hides a
    torn final entry — so whatever this returns is safe to resume from.

    Ensemble runs checkpoint into member-indexed stores
    (``ensemble/io.py``); the resumable step is then the MINIMUM
    durable step across member stores — the member analog of the
    multi-host quorum: a crash mid-boundary (some members saved, some
    not) rolls the whole ensemble back to the last step every member
    holds.

    ``max_step`` caps the answer at the last *verified* boundary: the
    SDC recovery path must not resume from a durable-but-unscreened
    entry written after the screener's last clean check
    (``resilience/sdc.py``).
    """
    if not settings.checkpoint:
        return None
    from .integrity import latest_durable_step_replicated

    ens = getattr(settings, "ensemble", None)
    if ens is not None:
        from ..ensemble.io import member_path

        steps = [
            latest_durable_step_replicated(
                member_path(settings.checkpoint_output, i, ens.n),
                max_step=max_step,
            )
            for i in range(ens.n)
        ]
        if any(s is None for s in steps):
            return None
        return min(steps)
    # Per store, the best step ANY replica serves (docs/RESILIENCE.md
    # "Data integrity"): a half-written or quarantined primary entry
    # must not drag the resume point down while a mirror holds it.
    return latest_durable_step_replicated(settings.checkpoint_output,
                                          max_step=max_step)


def _resolved_language(settings) -> str:
    from ..config.settings import KERNEL_LANGUAGES

    return KERNEL_LANGUAGES.get(
        settings.kernel_language.lower(), settings.kernel_language.lower()
    )


def _apply_resume(settings, resume: Optional[int], actions: list) -> None:
    """Point ``settings`` at the agreed restart step (or from-scratch)."""
    if resume is not None:
        settings.restart = True
        settings.restart_input = settings.checkpoint_output
        settings.restart_step = resume
        actions.append(f"resumed_from_checkpoint_step_{resume}")
    else:
        # No durable checkpoint (anywhere, under a quorum): restart the
        # trajectory from scratch — unless the operator's own restart
        # settings already point somewhere; leave those alone.
        if not settings.restart:
            actions.append("restarted_from_scratch")
        else:
            actions.append("restarted_from_configured_checkpoint")


def supervise(settings, *, n_devices: Optional[int] = None, seed: int = 0,
              sim_factory=None, reshape_poll=None):
    """Run ``driver.run_once`` under the restart loop; returns the
    completed attempt's :class:`~..simulation.Simulation`.

    ``settings`` is mutated across attempts (restart target, degraded
    kernel language) — the supervisor owns the run's lifecycle, and the
    final settings describe how the run actually finished. Multi-host
    runs agree on every restart through :mod:`.rendezvous` (cluster-max
    attempt counter, cluster-min checkpoint quorum). ``sim_factory``
    passes through to ``run_once`` (the serve worker fleet's
    warm-ensemble seam, ``serve/worker.py``) — every restart attempt
    asks the factory again, so a warm engine is rebound per attempt.
    ``reshape_poll`` likewise passes through to every attempt — the
    serve elastic controller's between-rounds live-reshape hook
    (docs/RESHARD.md) keeps polling across restarts.
    """
    from ..driver import run_once
    from ..utils.log import Logger
    from . import rendezvous as rdv_mod

    log = Logger(verbose=True)
    plan = FaultPlan.from_env(settings)
    journal = FaultJournal.from_env(settings)
    limit = resolve_max_restarts(settings)
    rdv = rdv_mod.from_env(settings)
    attempt = 0
    degraded: Optional[dict] = None
    corrupt_seen: set = set()
    # Devices the SDC screener has attributed a mismatch to, once: a
    # second attribution to the same device within this supervision is
    # a deterministic compute fault, not a cosmic ray — quarantine.
    sdc_seen: set = set()

    def _agree(resume_local: Optional[int]):
        """Quorum (attempt, restart step) across hosts; single-process
        runs pass the local view through unchanged."""
        nonlocal attempt
        if rdv is None:
            return resume_local
        attempt, resume = rdv.agree(attempt, resume_local)
        journal.record(
            event="rendezvous",
            round=rdv.round,
            attempt=attempt,
            local_step=-1 if resume_local is None else resume_local,
            quorum_step=-1 if resume is None else resume,
            procs=rdv.nprocs,
        )
        # Mesh agreement (docs/RESHARD.md): the replacement slice may
        # be a different shape than the one that checkpointed — every
        # host publishes its local device count and mesh proposal, and
        # all adopt the same topology BEFORE the restoring attempt
        # builds its Simulation (the adopted dims are pinned through
        # GS_TPU_MESH_DIMS, the same channel an operator uses). The
        # elastic restore path then reshards to it.
        import jax

        forced = env_str("GS_TPU_MESH_DIMS", "")
        proposal = (
            tuple(int(x) for x in forced.split(",")) if forced else None
        )
        mesh = rdv.agree_mesh(jax.local_device_count(), proposal)
        if mesh["dims"] is not None:
            os.environ["GS_TPU_MESH_DIMS"] = ",".join(
                str(d) for d in mesh["dims"]
            )
        journal.record(
            event="mesh_agreement",
            round=rdv.round,
            attempt=attempt,
            devices=mesh["devices"],
            dims=mesh["dims"],
            procs=mesh["procs"],
        )
        return resume

    # A previous launch that ended in a graceful preemption exit or a
    # watchdog hard exit left a resume marker as its final journal
    # line: restart from the (quorum) durable checkpoint immediately
    # instead of waiting for this launch to fail first.
    marker = resume_marker(journal.path)
    if marker is not None and not settings.restart:
        actions: list = []
        _apply_resume(settings, _agree(latest_durable_checkpoint(settings)),
                      actions)
        journal.record(
            event="recovery",
            kind="preemption" if marker["event"] == "graceful_shutdown"
            else "hang",
            attempt=attempt,
            after=marker["event"],
            action=";".join(actions),
        )
        log.info(
            f"supervisor: resuming after {marker['event']} "
            f"with [{', '.join(actions)}]"
        )

    while True:
        ctx = SupervisorContext(
            plan=plan, journal=journal, attempt=attempt, degraded=degraded
        )
        try:
            return run_once(
                settings, n_devices=n_devices, seed=seed, context=ctx,
                sim_factory=sim_factory, reshape_poll=reshape_poll,
            )
        except BaseException as exc:  # noqa: BLE001 — classify, then re-raise
            if isinstance(exc, GracefulShutdown):
                # A real preemption signal: the scheduler wants this
                # process gone — never restart in-place. run_once
                # already journaled the graceful_shutdown marker; the
                # CLI exits EXIT_PREEMPTED and the next supervised
                # launch auto-resumes from it (resume_marker above).
                raise
            kind = classify_failure(exc)
            # The failed attempt's phase accumulation, tagged by
            # attempt: RunStats dies with the attempt, the journal (and
            # so the final stats' faults section) keeps the per-attempt
            # wall-time attribution gs_report.py renders.
            if ctx.stats is not None and ctx.stats.phases:
                journal.record(
                    event="attempt_phases",
                    attempt=attempt,
                    kind=kind or "fatal",
                    phases_s={k: round(v, 6)
                              for k, v in ctx.stats.phases.items()},
                    steps=ctx.stats.counters.get("steps", 0),
                )
            if kind is None:
                journal.record(
                    event="gave_up",
                    kind="fatal",
                    attempt=attempt,
                    error=f"{type(exc).__name__}: {exc}",
                )
                raise

            if kind == "corruption":
                # Detected silent corruption is restartable WITH
                # failover — but only once per corrupt site: the same
                # step corrupting again means every replica (or the
                # re-snapshot) served rotten data, and an infinite
                # restart loop on a rotten store is the one recovery
                # this layer must never attempt.
                sig = _corruption_signature(exc)
                journal.record(
                    event="corruption",
                    step=sig[0],
                    detail=f"{type(exc).__name__}: {exc}",
                )
                if sig in corrupt_seen:
                    journal.record(
                        event="gave_up", kind=kind, attempt=attempt,
                        error=f"{type(exc).__name__}: {exc}",
                        reason=(
                            "repeated corruption of the same step — "
                            "non-transient, refusing to restart-loop"
                        ),
                    )
                    raise
                corrupt_seen.add(sig)

            sdc_actions: list = []
            sdc_scratch = False
            if kind == "sdc":
                # Compute-path SDC ladder (docs/RESILIENCE.md "Silent
                # data corruption"): first mismatch attributed to a
                # device → restart from the last VERIFIED checkpoint
                # (a transient upset replays clean); a SECOND mismatch
                # attributed to the SAME device → deterministic fault,
                # quarantine it so the restarting attempt's device
                # selection (and every fleet peer) excludes it.
                from .sdc import quarantine_device, usable_devices

                dev = getattr(exc, "device", None)
                if dev is not None and dev in sdc_seen:
                    quarantine_device(
                        dev, journal=journal,
                        step=getattr(exc, "step", None),
                        reason="repeated SDC attribution to this device",
                    )
                    sdc_actions.append(f"quarantined_{dev}")
                    if not usable_devices():
                        journal.record(
                            event="gave_up", kind=kind, attempt=attempt,
                            error=f"{type(exc).__name__}: {exc}",
                            reason="every device quarantined — no "
                                   "compute inventory left to restart on",
                        )
                        raise
                elif dev is not None:
                    sdc_seen.add(dev)
                verified = getattr(exc, "verified_step", None)
                if verified is None:
                    # Nothing this attempt wrote was ever screened —
                    # the trajectory restarts from scratch (or from the
                    # operator's own configured restart point).
                    sdc_scratch = True
                    sdc_actions.append("no_verified_boundary")

            # Cluster consensus BEFORE the budget check: the adopted
            # attempt counter is the cluster max, so GS_MAX_RESTARTS
            # bounds the whole cluster, not each rank independently.
            try:
                if kind == "sdc":
                    resume_local = (
                        None if sdc_scratch else latest_durable_checkpoint(
                            settings,
                            max_step=getattr(exc, "verified_step", None),
                        )
                    )
                else:
                    resume_local = latest_durable_checkpoint(settings)
                resume = _agree(resume_local)
            except rdv_mod.RendezvousTimeout as e:
                journal.record(
                    event="gave_up", kind=kind, attempt=attempt,
                    error=f"{type(exc).__name__}: {exc}",
                    reason=f"restart rendezvous failed: {e}",
                )
                raise

            if attempt >= limit:
                journal.record(
                    event="gave_up",
                    kind=kind,
                    attempt=attempt,
                    error=f"{type(exc).__name__}: {exc}",
                )
                raise

            actions = sdc_actions
            if kind == "kernel":
                lang = _resolved_language(settings)
                if lang in ("pallas", "auto"):
                    degraded = {
                        "degraded_from": lang,
                        "degraded_reason": f"{type(exc).__name__}: {exc}",
                        "degraded_at_attempt": attempt,
                    }
                    settings.kernel_language = "XLA"
                    actions.append("degraded_pallas_to_xla")
                else:
                    # Already on XLA: a kernel failure there has no
                    # softer language to fall back to.
                    journal.record(
                        event="gave_up", kind=kind, attempt=attempt,
                        error=f"{type(exc).__name__}: {exc}",
                        reason="kernel failure with no degradation left",
                    )
                    raise

            _apply_resume(settings, resume, actions)

            from ..obs import metrics as obs_metrics

            obs_metrics.get_metrics().counter("restarts", kind=kind).inc()
            delay = restart_backoff(attempt, kind)
            journal.record(
                event="recovery",
                kind=kind,
                attempt=attempt,
                error=f"{type(exc).__name__}: {exc}",
                action=";".join(actions),
                backoff_s=round(delay, 3),
            )
            log.info(
                f"supervisor: {kind} failure "
                f"({type(exc).__name__}: {exc}); attempt "
                f"{attempt + 1}/{limit} recovers with "
                f"[{', '.join(actions)}] after {delay:.2f}s"
            )
            time.sleep(delay)
            attempt += 1
