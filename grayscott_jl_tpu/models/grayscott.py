"""The Gray-Scott reaction-diffusion model — the framework's flagship model.

System (reference ``README.md:8-11``):

    u_t = Du * lap(u) - u*v^2 + F*(1-u) + noise*U(-1,1)
    v_t = Dv * lap(v) + u*v^2 - (F+k)*v

integrated with explicit Euler on a cubic grid of side ``L`` with a 1-cell
frozen ghost shell (u=1, v=0) as the boundary condition.

Design differences from the reference (idiomatic JAX):

* Fields are interior-shaped ``(L, L, L)`` immutable arrays; the ghost shell
  is materialized functionally at compute time (single device: constant pad;
  distributed: halo exchange in ``parallel/halo.py``). The reference instead
  carries mutable ghost-padded arrays plus explicit double buffers
  (``Structs.jl:82-93``); in JAX the "swap" is just returning new arrays
  (``public.jl:67-68`` made free).
* Noise comes from the framework's position-keyed counter-hash stream
  (``ops/noise.py``): each draw is a function of (key, absolute step,
  global cell coordinate), so restarts, step chunking, shard layout, and
  temporal fusion all reproduce the same trajectory — the reference's
  global-RNG ``rand(Distributions.Uniform(-1,1))``
  (``Simulation_CPU.jl:101-103``) is not reproducible across thread
  schedules.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from ..config.settings import Settings
from ..ops import stencil

#: Half-width of the seeded center cube (reference ``Simulation_CPU.jl:31``).
SEED_HALF_WIDTH = 6
SEED_U = 0.25
SEED_V = 0.33


class Params(NamedTuple):
    """Gray-Scott parameters as dtype-typed scalars (a JAX pytree).

    Passing these as traced values means changing F/k/Du/Dv/dt does not
    trigger recompilation.
    """

    Du: jnp.ndarray
    Dv: jnp.ndarray
    F: jnp.ndarray
    k: jnp.ndarray
    dt: jnp.ndarray
    noise: jnp.ndarray

    @classmethod
    def from_settings(cls, settings: Settings, dtype) -> "Params":
        return cls(
            Du=jnp.asarray(settings.Du, dtype),
            Dv=jnp.asarray(settings.Dv, dtype),
            F=jnp.asarray(settings.F, dtype),
            k=jnp.asarray(settings.k, dtype),
            dt=jnp.asarray(settings.dt, dtype),
            noise=jnp.asarray(settings.noise, dtype),
        )


def seed_bounds(L: int) -> Tuple[int, int]:
    """Global index range (inclusive) of the seeded center cube.

    Reference: ``minL = Int64(L/2 - d); maxL = Int64(L/2 + d)`` with d=6
    (``Simulation_CPU.jl:31-35``) over 0-based global coordinates. The
    reference throws ``InexactError`` for odd L; we require even L with a
    clear error.
    """
    if L % 2 != 0:
        raise ValueError(
            f"L must be even (reference requires Int(L/2)); got L={L}"
        )
    return L // 2 - SEED_HALF_WIDTH, L // 2 + SEED_HALF_WIDTH


def init_fields(
    L: int,
    dtype,
    *,
    offsets: Tuple[int, int, int] = (0, 0, 0),
    sizes: Optional[Tuple[int, int, int]] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Initialize (u, v) for a local block of the global ``L^3`` grid.

    u = 1 everywhere, v = 0, except a seeded cube
    ``[L/2-6, L/2+6]^3`` (inclusive) where u=0.25, v=0.33
    (reference ``Simulation_CPU.jl:23-57``). ``offsets``/``sizes`` select the
    block owned by this shard in global 0-based coordinates (whole grid by
    default); the seed region is intersected with the block, mirroring the
    reference's ``is_inside`` guard (``Common.jl:34-47``).

    Returns interior-shaped arrays (no ghost cells).
    """
    if sizes is None:
        sizes = (L, L, L)
    lo, hi = seed_bounds(L)

    u = jnp.full(sizes, stencil.U_BOUNDARY, dtype=dtype)
    v = jnp.full(sizes, stencil.V_BOUNDARY, dtype=dtype)

    # Intersect [lo, hi] (global, inclusive) with [off, off+size) per axis.
    slices = []
    empty = False
    for off, size in zip(offsets, sizes):
        a = max(lo - off, 0)
        b = min(hi + 1 - off, size)
        if a >= b:
            empty = True
            break
        slices.append(slice(a, b))
    if not empty:
        u = u.at[tuple(slices)].set(jnp.asarray(SEED_U, dtype))
        v = v.at[tuple(slices)].set(jnp.asarray(SEED_V, dtype))
    return u, v


def noise_field(key_i32, step, shape, dtype, noise: jnp.ndarray,
                offsets=(0, 0, 0), row=None) -> jnp.ndarray:
    """Pre-scaled noise term ``noise * U(-1, 1)`` per cell from the
    position-keyed stream (``ops/noise.py``) — the reproducible
    replacement for the reference's per-cell global-RNG
    ``rand(Distributions.Uniform(-1,1))`` (``Simulation_CPU.jl:101-103``).

    ``key_i32`` is int32[2] raw key data, ``step`` the absolute step
    index, ``offsets``/``row`` the block's global origin and the global
    grid side (for sharded blocks).
    """
    from ..ops.noise import uniform_pm1_block

    unit = uniform_pm1_block(
        key_i32, step, offsets, shape,
        shape[2] if row is None else row, dtype,
    )
    return noise * unit


