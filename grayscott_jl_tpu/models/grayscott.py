"""The Gray-Scott reaction-diffusion model — the framework's flagship model.

System (reference ``README.md:8-11``):

    u_t = Du * lap(u) - u*v^2 + F*(1-u) + noise*U(-1,1)
    v_t = Dv * lap(v) + u*v^2 - (F+k)*v

integrated with explicit Euler on a cubic grid of side ``L`` with a 1-cell
frozen ghost shell (u=1, v=0) as the boundary condition.

This module is the flagship :class:`~.base.Model` instance: the fields,
boundary constants, parameter declaration, reaction, and init below are
*declaration*, consumed by the shared execution machinery
(``ops/stencil.py`` n-field update, ``parallel/`` halo exchange and
temporal blocking, ``simulation.py``) exactly like every other
registered model's. The fused Pallas TPU kernel is generated from this
declaration like any other model's (``ops/kernelgen`` trace-inlines the
reaction into ``ops/pallas_stencil``'s slab pipeline) — Gray-Scott is
the generator's flagship instance, whose generated kernel is asserted
bitwise-identical to the hand-written kernel it replaced
(``tests/golden/pallas_hand_kernel.npz``). One thing remains
Gray-Scott-privileged: the reference-parity flat TOML keys
(``F``/``k``/``Du``/``Dv``) stay valid param spellings via
``legacy_keys`` — reference configs run unmodified, while the
``[model]`` table works too.

Design differences from the reference (idiomatic JAX):

* Fields are interior-shaped ``(L, L, L)`` immutable arrays; the ghost
  shell is materialized functionally at compute time (single device:
  constant pad; distributed: halo exchange in ``parallel/halo.py``).
* Noise comes from the framework's position-keyed counter-hash stream
  (``ops/noise.py``): each draw is a function of (key, absolute step,
  global cell coordinate), so restarts, step chunking, shard layout, and
  temporal fusion all reproduce the same trajectory — the reference's
  global-RNG ``rand(Distributions.Uniform(-1,1))``
  (``Simulation_CPU.jl:101-103``) is not reproducible across thread
  schedules.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, NamedTuple, Optional, Tuple

from . import base

if TYPE_CHECKING:  # pragma: no cover — annotation-only (keeps this
    import jax.numpy as jnp  # module, and the registry, JAX-free to import)

#: Frozen ghost-shell boundary values. In the reference, ghost layers
#: are initialized to u=1, v=0 (``Simulation_CPU.jl:23-24``) and — with
#: no neighbor to exchange with (``MPI.PROC_NULL``) — stay frozen,
#: acting as Dirichlet boundary data on the global domain edge. These
#: are Gray-Scott model data: shared code (``ops/``, ``parallel/``)
#: receives boundary values through the model declaration, never from
#: constants of its own.
U_BOUNDARY = 1.0
V_BOUNDARY = 0.0

#: Half-width of the seeded center cube (reference ``Simulation_CPU.jl:31``).
SEED_HALF_WIDTH = 6
SEED_U = 0.25
SEED_V = 0.33


class Params(NamedTuple):
    """Gray-Scott parameters as dtype-typed scalars (a JAX pytree).

    Passing these as traced values means changing F/k/Du/Dv/dt does not
    trigger recompilation.
    """

    Du: jnp.ndarray
    Dv: jnp.ndarray
    F: jnp.ndarray
    k: jnp.ndarray
    dt: jnp.ndarray
    noise: jnp.ndarray

    @classmethod
    def from_settings(cls, settings, dtype) -> "Params":
        """Params for one run — routed through the model declaration
        (``[model]`` table wins over the legacy flat keys; unknown
        table keys raise :class:`~.base.SettingsError`)."""
        return MODEL.make_params(settings, dtype)


def seed_bounds(L: int) -> Tuple[int, int]:
    """Global index range (inclusive) of the seeded center cube.

    Reference: ``minL = Int64(L/2 - d); maxL = Int64(L/2 + d)`` with d=6
    (``Simulation_CPU.jl:31-35``) over 0-based global coordinates. The
    reference throws ``InexactError`` for odd L; we require even L with a
    clear error.
    """
    if L % 2 != 0:
        raise ValueError(
            f"L must be even (reference requires Int(L/2)); got L={L}"
        )
    return L // 2 - SEED_HALF_WIDTH, L // 2 + SEED_HALF_WIDTH


def init_fields(
    L: int,
    dtype,
    *,
    offsets: Tuple[int, int, int] = (0, 0, 0),
    sizes: Optional[Tuple[int, int, int]] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Initialize (u, v) for a local block of the global ``L^3`` grid.

    u = 1 everywhere, v = 0, except a seeded cube
    ``[L/2-6, L/2+6]^3`` (inclusive) where u=0.25, v=0.33
    (reference ``Simulation_CPU.jl:23-57``). ``offsets``/``sizes`` select
    the block owned by this shard in global 0-based coordinates (whole
    grid by default); the seed region is intersected with the block,
    mirroring the reference's ``is_inside`` guard (``Common.jl:34-47``).

    Returns interior-shaped arrays (no ghost cells).
    """
    return base.seeded_box_init(
        L, dtype,
        backgrounds=(U_BOUNDARY, V_BOUNDARY),
        seed_values=(SEED_U, SEED_V),
        half_width=SEED_HALF_WIDTH,
        offsets=offsets, sizes=sizes,
    )


def reaction(fields, laps, noise_u, params):
    """The Gray-Scott time derivatives (``Simulation_CPU.jl:92-112``):

        du = Du*lap(u) - u*v^2 + F*(1-u) + noise*U(-1,1)
        dv = Dv*lap(v) + u*v^2 - (F+k)*v

    ``noise_u`` is the pre-scaled noise field ``noise * U(-1,1)`` (or
    0.0 for the noiseless path); only ``du`` receives noise, as in the
    reference. The expression order here is load-bearing: it reproduces
    the pre-framework update's dataflow graph exactly, which is what
    keeps the refactored trajectory byte-identical to the golden one
    (``tests/golden/``).
    """
    import jax.numpy as jnp

    u, v = fields
    lap_u, lap_v = laps
    one = jnp.asarray(1.0, u.dtype)

    uvv = u * v * v
    du = params.Du * lap_u - uvv + params.F * (one - u) + noise_u
    dv = params.Dv * lap_v + uvv - (params.F + params.k) * v
    return du, dv


def noise_field(key_i32, step, shape, dtype, noise: jnp.ndarray,
                offsets=(0, 0, 0), row=None) -> jnp.ndarray:
    """Pre-scaled noise term ``noise * U(-1, 1)`` per cell from the
    position-keyed stream (``ops/noise.py``) — the reproducible
    replacement for the reference's per-cell global-RNG
    ``rand(Distributions.Uniform(-1,1))`` (``Simulation_CPU.jl:101-103``).

    ``key_i32`` is int32[2] raw key data, ``step`` the absolute step
    index, ``offsets``/``row`` the block's global origin and the global
    grid side (for sharded blocks).
    """
    from ..ops.noise import uniform_pm1_block

    unit = uniform_pm1_block(
        key_i32, step, offsets, shape,
        shape[2] if row is None else row, dtype,
    )
    return noise * unit


MODEL = base.register(base.Model(
    name="grayscott",
    field_names=("u", "v"),
    boundaries=(U_BOUNDARY, V_BOUNDARY),
    param_decls={"Du": 0.05, "Dv": 0.1, "F": 0.04, "k": 0.0},
    reaction=reaction,
    init=init_fields,
    params_cls=Params,
    legacy_keys={"Du": "Du", "Dv": "Dv", "F": "F", "k": "k"},
    description="Gray-Scott cubic autocatalysis (reference parity)",
))
