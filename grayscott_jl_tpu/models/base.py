"""The model framework: reaction-diffusion models as *data*.

Every model this framework runs is one instance of the same shape —
"pointwise reaction + linear 7-point stencil" — so a model is fully
described by a declaration, not by code threaded through the execution
machinery:

* **named fields** with per-field frozen-ghost boundary values (the
  Dirichlet constants the halo exchange delivers at global edges),
* **typed params** — a NamedTuple pytree of dtype-typed scalars whose
  model-specific entries are declared with defaults (``None`` =
  required in the ``[model]`` TOML table), always extended by the
  framework-level ``dt`` and ``noise``,
* a pure **reaction** function over field values + Laplacians +
  pre-scaled noise, returning the time derivatives,
* an **init** function producing the initial fields for any sub-block
  of the global grid (multi-host sharded construction).

The distributed execution machinery — halo exchange, split-phase comm
overlap, temporal blocking, autotune, resilience, ensembles, I/O —
consumes only this declaration and is shared by every model with zero
per-model parallelism code (the separation argued by the stencil-DSL
shared-compilation-stack line of work; PAPERS.md). Gray-Scott
(``models/grayscott.py``) is the flagship registered instance. The
fused Pallas TPU kernel is GENERATED from the declaration too
(``ops/kernelgen`` trace-inlines the pure reaction into the slab
pipeline): eligibility is a feasibility property of the reaction's
jaxpr — elementwise ops only — checked by
``kernelgen.generation_gate_reason`` and recorded as the
``kernel_gate`` provenance in ``kernel_selection``, not a per-model
capability flag.

Adding a model is ~40 lines: declare fields/params/reaction/init, call
:func:`register`. See ``docs/MODELS.md`` for the walkthrough.
"""

from __future__ import annotations

from collections import namedtuple
from typing import Callable, Dict, Mapping, Optional, Sequence, Tuple


class SettingsError(ValueError):
    """A configuration error the operator must fix — raised loudly at
    parse/construction time, never silently defaulted around."""


#: Framework-level parameters appended to every model's Params pytree:
#: the explicit-Euler step size and the noise amplitude. They are flat
#: ``Settings`` keys (``dt`` / ``noise``), not ``[model]`` table keys.
FRAMEWORK_PARAMS = ("dt", "noise")


class Model:
    """One registered reaction-diffusion model.

    ``param_decls`` maps model-specific parameter names to their default
    values (``None`` = required: omitting it in the ``[model]`` table is
    a loud :class:`SettingsError`). ``reaction(fields, laps, noise,
    params)`` receives interior-shaped field arrays, their Laplacians in
    the same order, the pre-scaled noise array (or a 0.0 scalar when
    noise is off), and the typed params; it returns the per-field time
    derivatives. ``init(L, dtype, offsets=..., sizes=...)`` returns the
    initial interior-shaped field blocks for a sub-box of the global
    grid.

    ``legacy_keys`` maps a param name to a flat ``Settings`` attribute
    supplying its default (Gray-Scott's reference-parity F/k/Du/Dv
    keys); for every other model, params come from the ``[model]``
    table alone.
    """

    def __init__(
        self,
        *,
        name: str,
        field_names: Sequence[str],
        boundaries: Sequence[float],
        param_decls: Mapping[str, Optional[float]],
        reaction: Callable,
        init: Callable,
        params_cls: Optional[type] = None,
        legacy_keys: Optional[Mapping[str, str]] = None,
        description: str = "",
    ):
        if len(field_names) != len(boundaries):
            raise ValueError(
                f"model {name!r}: {len(field_names)} fields but "
                f"{len(boundaries)} boundary values"
            )
        overlap = set(param_decls) & set(FRAMEWORK_PARAMS)
        if overlap:
            raise ValueError(
                f"model {name!r} redeclares framework params "
                f"{sorted(overlap)}"
            )
        self.name = str(name)
        self.field_names: Tuple[str, ...] = tuple(field_names)
        self.boundaries: Tuple[float, ...] = tuple(
            float(b) for b in boundaries
        )
        self.param_names: Tuple[str, ...] = tuple(param_decls)
        self.param_defaults: Dict[str, Optional[float]] = dict(param_decls)
        self.reaction = reaction
        self.init = init
        self.legacy_keys = dict(legacy_keys or {})
        self.description = description
        #: The typed Params pytree class: model params in declaration
        #: order, then the framework's (dt, noise). Gray-Scott passes
        #: its hand-written NamedTuple so the pre-refactor pytree
        #: structure (and everything keyed on it) is preserved.
        self.params_cls = params_cls or namedtuple(
            f"{self.name.capitalize()}Params",
            self.param_names + FRAMEWORK_PARAMS,
        )
        missing = set(self.param_names + FRAMEWORK_PARAMS) - set(
            self.params_cls._fields
        )
        if missing:
            raise ValueError(
                f"model {name!r}: params_cls lacks fields {sorted(missing)}"
            )

    @property
    def n_fields(self) -> int:
        return len(self.field_names)

    # ------------------------------------------------------------ params

    def validate_table(self, table: Mapping) -> None:
        """Reject a ``[model]`` TOML table with unknown or missing keys
        — loudly, naming the model (the silent-default trap this
        replaces is exactly how a misspelled ``Dv`` burns a campaign)."""
        unknown = set(table) - set(self.param_names)
        if unknown:
            raise SettingsError(
                f"[model] table for model {self.name!r} has unknown "
                f"parameter keys {sorted(unknown)}; accepted: "
                f"{sorted(self.param_names)}"
            )
        missing = [
            p for p in self.param_names
            if p not in table and self.param_defaults[p] is None
            and p not in self.legacy_keys
        ]
        if missing:
            raise SettingsError(
                f"model {self.name!r} requires parameter(s) "
                f"{sorted(missing)} in the [model] table"
            )
        for key, value in table.items():
            if isinstance(value, bool) or not isinstance(
                value, (int, float)
            ):
                raise SettingsError(
                    f"[model] parameter {key!r} for model {self.name!r} "
                    f"must be a number, got {value!r}"
                )

    def resolve_param_values(self, settings) -> Dict[str, float]:
        """Model-specific parameter values for one run, resolved
        through THIS model's declaration: ``[model]`` table entry >
        legacy flat Settings key (Gray-Scott only) > declared default.
        Raises :class:`SettingsError` (naming the model) on unknown or
        missing keys — never a silent default for a typo."""
        table = dict(getattr(settings, "model_params", None) or {})
        self.validate_table(table)
        values: Dict[str, float] = {}
        for p in self.param_names:
            if p in table:
                values[p] = float(table[p])
            elif p in self.legacy_keys:
                values[p] = float(getattr(settings, self.legacy_keys[p]))
            else:
                default = self.param_defaults[p]
                assert default is not None  # validate_table guarantees
                values[p] = float(default)
        return values

    def make_params(self, settings, dtype):
        """The typed Params pytree for one run — dtype-typed scalars,
        traced (not baked) so parameter changes never recompile."""
        import jax.numpy as jnp

        values = self.resolve_param_values(settings)
        values["dt"] = float(settings.dt)
        values["noise"] = float(settings.noise)
        return self.params_cls(**{
            f: jnp.asarray(values[f], dtype)
            for f in self.params_cls._fields
        })

    def describe(self) -> dict:
        """JSON-able declaration summary for stats/store provenance."""
        return {
            "name": self.name,
            "fields": list(self.field_names),
            "boundaries": list(self.boundaries),
            "params": list(self.param_names),
        }


# ---------------------------------------------------------------- registry

_REGISTRY: Dict[str, Model] = {}


def register(model: Model) -> Model:
    """Register ``model`` under its name (idempotent re-registration of
    the same object; a different object under a taken name is a bug)."""
    existing = _REGISTRY.get(model.name)
    if existing is not None and existing is not model:
        raise ValueError(f"model {model.name!r} is already registered")
    _REGISTRY[model.name] = model
    return model


def get_model(name: str) -> Model:
    """Look up a registered model by name; unknown names list what IS
    registered (the typo-facing error path)."""
    try:
        return _REGISTRY[str(name).lower()]
    except KeyError:
        raise SettingsError(
            f"Unknown model {name!r}; registered models: "
            f"{', '.join(sorted(_REGISTRY))}"
        ) from None


def available_models() -> Tuple[str, ...]:
    return tuple(sorted(_REGISTRY))


# ------------------------------------------------------------ init helper

def seeded_box_init(
    L: int,
    dtype,
    *,
    backgrounds: Sequence[float],
    seed_values: Sequence[float],
    half_width: int,
    offsets: Tuple[int, int, int] = (0, 0, 0),
    sizes: Optional[Tuple[int, int, int]] = None,
):
    """Shared initial condition: uniform backgrounds with a seeded
    center cube ``[L/2-half_width, L/2+half_width]^3`` (inclusive) —
    the reference's ``Simulation_CPU.jl:23-57`` pattern, generalized to
    any field count. ``offsets``/``sizes`` select a local block in
    global 0-based coordinates; the seed region is intersected with the
    block. Even ``L`` is required (the reference throws
    ``InexactError`` for odd L; we error clearly)."""
    import jax.numpy as jnp

    if L % 2 != 0:
        raise ValueError(
            f"L must be even (reference requires Int(L/2)); got L={L}"
        )
    if sizes is None:
        sizes = (L, L, L)
    lo, hi = L // 2 - half_width, L // 2 + half_width

    fields = [
        jnp.full(sizes, bg, dtype=dtype) for bg in backgrounds
    ]
    # Intersect [lo, hi] (global, inclusive) with [off, off+size) per axis.
    slices = []
    empty = False
    for off, size in zip(offsets, sizes):
        a = max(lo - off, 0)
        b = min(hi + 1 - off, size)
        if a >= b:
            empty = True
            break
        slices.append(slice(a, b))
    if not empty:
        fields = [
            f.at[tuple(slices)].set(jnp.asarray(sv, dtype))
            for f, sv in zip(fields, seed_values)
        ]
    return tuple(fields)
