"""FitzHugh–Nagumo — excitable-media activator/inhibitor dynamics.

    v_t = Dv * lap(v) + v - v^3/3 - w + I + noise*U(-1,1)
    w_t = Dw * lap(w) + eps * (v + a - b*w)

A registered :class:`~.base.Model`: the declaration below is ALL the
FitzHugh–Nagumo-specific code in the framework — including the fused
Pallas TPU kernel, which ``ops/kernelgen`` generates by trace-inlining
the reaction below. The activator ``v`` is seeded
super-threshold in the center cube over a quiescent background, so a
single excitation wave propagates outward — the classic excitable-media
scenario.

Config::

    [model]
    name = "fhn"
    a = 0.7
    b = 0.8
    eps = 0.08
    I = 0.5
    Dv = 0.2
    Dw = 0.0
"""

from __future__ import annotations

from . import base

V_BOUNDARY = 0.0
W_BOUNDARY = 0.0

SEED_HALF_WIDTH = 6
SEED_V = 1.0
SEED_W = 0.0


def reaction(fields, laps, noise_v, params):
    import jax.numpy as jnp

    v, w = fields
    lap_v, lap_w = laps
    third = jnp.asarray(1.0 / 3.0, v.dtype)

    dv = (params.Dv * lap_v + v - v * v * v * third - w + params.I
          + noise_v)
    dw = params.Dw * lap_w + params.eps * (v + params.a - params.b * w)
    return dv, dw


def init_fields(L, dtype, *, offsets=(0, 0, 0), sizes=None):
    return base.seeded_box_init(
        L, dtype,
        backgrounds=(V_BOUNDARY, W_BOUNDARY),
        seed_values=(SEED_V, SEED_W),
        half_width=SEED_HALF_WIDTH,
        offsets=offsets, sizes=sizes,
    )


MODEL = base.register(base.Model(
    name="fhn",
    field_names=("v", "w"),
    boundaries=(V_BOUNDARY, W_BOUNDARY),
    param_decls={
        "a": 0.7, "b": 0.8, "eps": 0.08, "I": 0.5,
        "Dv": 0.2, "Dw": 0.0,
    },
    reaction=reaction,
    init=init_fields,
    description="FitzHugh-Nagumo excitable media",
))
