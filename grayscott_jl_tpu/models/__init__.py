"""Model registry: reaction-diffusion models as data.

A model = (named fields, per-field boundary values, typed params
declaration, pure reaction function, init function) — see
``models/base.py`` for the protocol and ``docs/MODELS.md`` for how to
add one. Importing this package registers the built-in models:

* ``grayscott``   — the flagship (reference parity)
* ``brusselator`` — trimolecular autocatalysis
* ``fhn``         — FitzHugh–Nagumo excitable media
* ``heat``        — plain one-field diffusion

The execution machinery (``simulation.py``, ``ops/``, ``parallel/``,
``ensemble/``, ``io/``) consumes only the declaration; no per-model
code exists outside this package.
"""

from __future__ import annotations

from .base import (  # noqa: F401
    FRAMEWORK_PARAMS,
    Model,
    SettingsError,
    available_models,
    get_model,
    register,
    seeded_box_init,
)

# Built-in model registrations (import order = registry population).
from . import grayscott  # noqa: F401,E402
from . import brusselator  # noqa: F401,E402
from . import fhn  # noqa: F401,E402
from . import heat  # noqa: F401,E402
