"""The Brusselator — trimolecular autocatalysis (Prigogine–Lefever).

    u_t = Du * lap(u) + A - (B+1)*u + u^2*v + noise*U(-1,1)
    v_t = Dv * lap(v) + B*u - u^2*v

A registered :class:`~.base.Model`: the declaration below is ALL the
Brusselator-specific code in the framework — halo exchange, split-phase
overlap, temporal blocking, autotune, resilience, ensembles, and I/O
come from the shared stack unchanged, and the fused Pallas TPU kernel
is generated from the reaction below (``ops/kernelgen``).

Boundary/background state is the homogeneous steady state of the
default parameters, ``(u, v) = (A, B/A) = (1, 3)``: the frozen ghost
shell holds the equilibrium, and patterns grow from the perturbed
center cube. The ghost constants are fixed model data (they do not
track a reconfigured A/B — the frame is Dirichlet data, not physics).

Config::

    [model]
    name = "brusselator"
    A = 1.0
    B = 3.0
    Du = 0.2
    Dv = 0.02
"""

from __future__ import annotations

from . import base

U_BOUNDARY = 1.0   # steady-state u = A (default A = 1)
V_BOUNDARY = 3.0   # steady-state v = B/A (defaults B = 3, A = 1)

SEED_HALF_WIDTH = 6
SEED_U = 0.5
SEED_V = 2.0


def reaction(fields, laps, noise_u, params):
    import jax.numpy as jnp

    u, v = fields
    lap_u, lap_v = laps
    one = jnp.asarray(1.0, u.dtype)

    uuv = u * u * v
    du = params.Du * lap_u + params.A - (params.B + one) * u + uuv + noise_u
    dv = params.Dv * lap_v + params.B * u - uuv
    return du, dv


def init_fields(L, dtype, *, offsets=(0, 0, 0), sizes=None):
    return base.seeded_box_init(
        L, dtype,
        backgrounds=(U_BOUNDARY, V_BOUNDARY),
        seed_values=(SEED_U, SEED_V),
        half_width=SEED_HALF_WIDTH,
        offsets=offsets, sizes=sizes,
    )


MODEL = base.register(base.Model(
    name="brusselator",
    field_names=("u", "v"),
    boundaries=(U_BOUNDARY, V_BOUNDARY),
    param_decls={"A": 1.0, "B": 3.0, "Du": 0.2, "Dv": 0.02},
    reaction=reaction,
    init=init_fields,
    description="Brusselator trimolecular autocatalysis",
))
