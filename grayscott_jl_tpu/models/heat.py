"""Plain heat diffusion — the minimal one-field model.

    T_t = D * lap(T) + noise*U(-1,1)

Deliberately trivial: it exists to pin the framework's n-field
generality (everything else ships two fields) and as the cheapest
smoke-test physics — a hot center cube relaxing toward the cold
Dirichlet frame. With ``noise`` set it becomes the stochastic heat
equation. This whole file is the model's entire footprint in the
framework; the distributed machinery is shared (XLA kernel path).

Config::

    [model]
    name = "heat"
    D = 0.2
"""

from __future__ import annotations

from . import base

T_BOUNDARY = 0.0

SEED_HALF_WIDTH = 6
SEED_T = 1.0


def reaction(fields, laps, noise_t, params):
    (lap_t,) = laps
    return (params.D * lap_t + noise_t,)


def init_fields(L, dtype, *, offsets=(0, 0, 0), sizes=None):
    return base.seeded_box_init(
        L, dtype,
        backgrounds=(T_BOUNDARY,),
        seed_values=(SEED_T,),
        half_width=SEED_HALF_WIDTH,
        offsets=offsets, sizes=sizes,
    )


MODEL = base.register(base.Model(
    name="heat",
    field_names=("T",),
    boundaries=(T_BOUNDARY,),
    param_decls={"D": 0.2},
    reaction=reaction,
    init=init_fields,
    description="Plain heat diffusion (one field)",
))
