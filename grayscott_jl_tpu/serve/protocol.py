"""The serve job-spec protocol: client JSON -> validated framework spec.

One job is one scenario of one registered model — exactly what a solo
CLI launch runs, and exactly what one MEMBER of a batched ensemble
runs (docs/ENSEMBLE.md). The scheduler exploits that equivalence: a
request validates here into a :class:`JobSpec`, packs with compatible
requests (same :func:`pack_key`) into one ``[ensemble]``-shaped batch,
and its results are byte-identical to the solo run it describes
(docs/SERVICE.md, "equality fine print").

Validation is LOUD and happens at admission: an unknown model, a
misspelled parameter, a missing required parameter, or a mistyped
value raises :class:`~..models.base.SettingsError` naming the problem,
and the HTTP layer hands that text straight back as the 400 body — a
typo can never burn a batch slot.

Stdlib-only and JAX-free to import, like ``config/`` and ``models/``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

from ..config.settings import PRECISIONS, Settings
from ..ensemble.spec import (
    EnsembleSettings,
    MemberSpec,
    member_param_fields,
)
from ..models import get_model
from ..models.base import SettingsError

__all__ = [
    "JobSpec",
    "PRIORITIES",
    "batch_settings",
    "pack_key",
    "parse_job",
    "resolved_params",
]

#: Named priority levels -> numeric rank (higher runs first). Clients
#: may also send a bare integer in [0, 9].
PRIORITIES: Dict[str, int] = {"low": 2, "normal": 5, "high": 8}

#: Keys a job-spec payload may carry; anything else is a loud error
#: (the silent-ignore trap the [model] table already closed).
JOB_SPEC_KEYS = frozenset({
    "tenant", "priority", "model", "params", "L", "steps", "plotgap",
    "checkpoint_freq", "dt", "noise", "seed", "precision",
    "halo_depth",
})


@dataclasses.dataclass(frozen=True)
class JobSpec:
    """One validated simulation request.

    ``params`` is the model-declared parameter table (validated against
    the registry declaration, defaults resolved at batch-build time the
    same way a ``[model]`` TOML table resolves). The remaining fields
    mirror the Settings keys that determine the compiled step program —
    they are the packing axes (:func:`pack_key`) — plus the per-member
    knobs (params/dt/noise/seed) that ride as runtime data in the
    vmapped launch.
    """

    tenant: str
    model: str
    L: int
    steps: int
    params: Tuple[Tuple[str, float], ...]
    dt: float = 0.2
    noise: float = 0.0
    seed: int = 0
    priority: int = PRIORITIES["normal"]
    plotgap: int = 0
    checkpoint_freq: int = 0
    precision: str = "Float32"
    halo_depth: int = 0

    def describe(self) -> dict:
        return {
            "tenant": self.tenant,
            "model": self.model,
            "L": self.L,
            "steps": self.steps,
            "params": dict(self.params),
            "dt": self.dt,
            "noise": self.noise,
            "seed": self.seed,
            "priority": self.priority,
            "plotgap": self.plotgap,
            "checkpoint_freq": self.checkpoint_freq,
            "precision": self.precision,
            "halo_depth": self.halo_depth,
        }


def _require(cond: bool, msg: str) -> None:
    if not cond:
        raise SettingsError(msg)


def _as_int(payload: dict, key: str, default: int, lo: int,
            hi: int) -> int:
    v = payload.get(key, default)
    _require(
        isinstance(v, int) and not isinstance(v, bool),
        f"job spec {key!r} must be an integer, got {v!r}",
    )
    _require(
        lo <= v <= hi,
        f"job spec {key!r} must be in [{lo}, {hi}], got {v}",
    )
    return int(v)


def _as_float(payload: dict, key: str, default: float) -> float:
    v = payload.get(key, default)
    _require(
        isinstance(v, (int, float)) and not isinstance(v, bool),
        f"job spec {key!r} must be a number, got {v!r}",
    )
    return float(v)


def parse_job(payload: Any, *, max_l: int = 256,
              max_steps: int = 1_000_000) -> JobSpec:
    """Validate one client payload into a :class:`JobSpec`.

    ``max_l`` / ``max_steps`` are the service's admission size caps
    (GS_SERVE_MAX_L / GS_SERVE_MAX_STEPS) — oversized requests are a
    *spec* error at the front door, not an OOM an hour into a batch.
    Raises :class:`SettingsError` with a client-presentable message.
    """
    _require(isinstance(payload, dict),
             f"job spec must be a JSON object, got {type(payload).__name__}")
    unknown = set(payload) - JOB_SPEC_KEYS
    _require(
        not unknown,
        f"job spec has unknown keys {sorted(unknown)}; accepted: "
        f"{sorted(JOB_SPEC_KEYS)}",
    )
    tenant = payload.get("tenant", "")
    _require(
        isinstance(tenant, str) and 0 < len(tenant) <= 64,
        "job spec needs a 'tenant' string (1-64 chars)",
    )
    model_name = payload.get("model", "grayscott")
    _require(isinstance(model_name, str),
             f"job spec 'model' must be a string, got {model_name!r}")
    model = get_model(model_name)  # unknown -> SettingsError w/ registry

    raw_params = payload.get("params", {})
    _require(isinstance(raw_params, dict),
             "job spec 'params' must be an object of model parameters")
    model.validate_table(raw_params)

    precision = payload.get("precision", "Float32")
    _require(
        precision in PRECISIONS,
        f"job spec 'precision' must be one of "
        f"{sorted(PRECISIONS)}, got {precision!r}",
    )

    prio = payload.get("priority", "normal")
    if isinstance(prio, str):
        _require(
            prio in PRIORITIES,
            f"job spec 'priority' must be one of "
            f"{sorted(PRIORITIES)} or an integer 0-9, got {prio!r}",
        )
        prio = PRIORITIES[prio]
    _require(
        isinstance(prio, int) and not isinstance(prio, bool)
        and 0 <= prio <= 9,
        f"job spec 'priority' must be 0-9, got {prio!r}",
    )

    L = _as_int(payload, "L", 32, 4, max_l)
    steps = _as_int(payload, "steps", 100, 1, max_steps)
    plotgap = _as_int(payload, "plotgap", 0, 0, max_steps)
    ckpt = _as_int(payload, "checkpoint_freq", 0, 0, max_steps)
    seed = _as_int(payload, "seed", 0, 0, 2**31 - 1)
    halo_depth = _as_int(payload, "halo_depth", 0, 0, 16)
    dt = _as_float(payload, "dt", 0.2)
    noise = _as_float(payload, "noise", 0.0)
    _require(dt > 0, f"job spec 'dt' must be > 0, got {dt}")

    return JobSpec(
        tenant=tenant,
        model=model.name,
        L=L,
        steps=steps,
        params=tuple(sorted(
            (k, float(v)) for k, v in raw_params.items()
        )),
        dt=dt,
        noise=noise,
        seed=seed,
        priority=int(prio),
        plotgap=plotgap,
        checkpoint_freq=ckpt,
        precision=precision,
        halo_depth=halo_depth,
    )


def pack_key(spec: JobSpec) -> Tuple:
    """The compatibility class two requests must share to ride one
    batched launch (docs/SERVICE.md, "packing rules").

    Everything that shapes the compiled step program or the step
    schedule is a key axis: the model (field count, reaction), L,
    steps and the output/checkpoint cadence (one launch advances all
    members on one boundary schedule), precision, the s-step exchange
    depth, and whether ANY noise is drawn (noise changes the traced
    program; keying on it also keeps a noiseless member's program
    identical to its noiseless solo run). Member params, dt, noise
    magnitude, and seeds are runtime data — they vmap, so they are
    deliberately NOT key axes.
    """
    return (
        spec.model, spec.L, spec.steps, spec.plotgap,
        spec.checkpoint_freq, spec.precision, spec.halo_depth,
        spec.noise != 0.0,
    )


def _member_values(spec: JobSpec, model) -> Tuple[Tuple[str, float], ...]:
    """The ordered member-parameter tuple for one job, defaults
    resolved through the model declaration like a ``[model]`` table."""
    table = dict(spec.params)
    values = {}
    for p in model.param_names:
        if p in table:
            values[p] = float(table[p])
        else:
            default = model.param_defaults[p]
            _require(
                default is not None,
                f"model {model.name!r} requires parameter {p!r}",
            )
            values[p] = float(default)
    values["dt"] = float(spec.dt)
    values["noise"] = float(spec.noise)
    fields = member_param_fields(model)
    return tuple((f, values[f]) for f in fields)


def resolved_params(spec: JobSpec) -> Tuple[Tuple[str, float], ...]:
    """The fully-resolved, canonically-ordered member parameters of one
    job — model defaults filled, dt/noise included, field order fixed
    by ``member_param_fields``. This is exactly the runtime data a
    packed slot receives, which makes it the parameter half of the
    result-cache identity (``serve/cache.py``): two specs with this
    tuple equal (plus equal pack-shaping fields and seed) run the same
    member and therefore produce bitwise-identical stores."""
    return _member_values(spec, get_model(spec.model))


def batch_settings(specs, *, n_slots: int, output: str,
                   checkpoint_output: str, names=None,
                   supervise: bool = False,
                   max_restarts: int = 3) -> Settings:
    """Build the Settings one packed launch runs: the shared pack-key
    axes as scalar settings, the jobs as ``[ensemble]`` members (in
    slot order), and ``n_slots - len(specs)`` trailing IDLE padding
    members — copies of slot 0's parameters with ``active=False``, so
    the executable keeps a canonical member count (the warm-cache key)
    while the padding writes no stores and perturbs no statistics.

    The batch runs headless inside a worker thread: the hang watchdog
    and the signal-based graceful shutdown are forced off (signal
    handlers belong to the serving process, not to worker threads);
    supervision (in-place restart of classified transient failures) is
    the worker fleet's call.
    """
    specs = list(specs)
    _require(bool(specs), "a batch needs at least one job")
    _require(n_slots >= len(specs),
             f"{len(specs)} jobs cannot ride {n_slots} slots")
    key = pack_key(specs[0])
    for s in specs[1:]:
        _require(
            pack_key(s) == key,
            "all jobs of a batch must share one pack key "
            f"({pack_key(s)} != {key})",
        )
    head = specs[0]
    model = get_model(head.model)
    names = list(names or [])
    members = []
    for i, s in enumerate(specs):
        members.append(MemberSpec(
            values=_member_values(s, model),
            seed=int(s.seed),
            name=str(names[i]) if i < len(names) else f"job{i}",
        ))
    for i in range(len(specs), n_slots):
        members.append(MemberSpec(
            values=_member_values(head, model),
            seed=0,
            name=f"idle{i}",
            active=False,
        ))
    ens = EnsembleSettings(
        members=tuple(members), member_shards=1, model=model.name,
    )
    checkpoint = head.checkpoint_freq > 0
    return Settings(
        L=head.L,
        steps=head.steps,
        plotgap=head.plotgap,
        dt=head.dt,
        noise=head.noise,
        output=output,
        checkpoint=checkpoint,
        checkpoint_freq=head.checkpoint_freq or 0,
        checkpoint_output=checkpoint_output,
        precision=head.precision,
        backend="CPU",
        kernel_language="Plain",
        halo_depth=head.halo_depth,
        model=model.name,
        supervise=supervise,
        max_restarts=max_restarts,
        watchdog="off",
        graceful_shutdown=False,
        ensemble=ens,
    )
