"""The distributed serve fleet: N front doors + M workers, one state.

The PR 13 service is one process — one ThreadingHTTPServer, one
in-memory queue, one thread fleet. This module scales it out
(ROADMAP item 4) by moving the scheduler state into a shared
filesystem KV namespace (``GS_SERVE_FLEET_DIR``) built on the PR 5
rendezvous publish primitive, so ANY front-door replica can admit,
route, status, and fail over any job, and ANY worker process can pull
compatible work:

* **put** is :func:`~..resilience.rendezvous.atomic_publish` (tmp +
  fsync + rename): readers see whole documents or nothing;
* **claim** is ``O_EXCL`` create: exactly one creator wins;
* **take** is ``os.rename``: exactly one mover wins — the primitive
  under queue pops, lease expiry, and resume adoption.

Namespace layout (all under the fleet dir)::

    members/<id>       role, pid, host:port, last heartbeat
    jobs/<id>          the full job document (spec + lifecycle)
    queue/<qkey>       pending-job markers; qkey sorts priority->FIFO
    claims/<id>/<qkey> claim-to-lease crash window markers
    leases/<batch>     running batch -> owning worker + expiry
    resume/<batch>     requeued batch awaiting re-adoption
    batches/<batch>/   the launch dirs (stores live here, shared FS)

**Fail-over.** A worker heartbeats its member doc and renews its batch
leases every ``GS_SERVE_HEARTBEAT_S``; when it dies, whichever
front-door replica's reaper first notices the expired lease *takes* it
(one winner) and converts it to a ``resume/`` entry — the next free
worker re-adopts the batch and resumes from the member-store
checkpoint quorum, exactly the single-process requeue path. A worker
that wedges past its lease and then wakes can at worst run a batch a
second time — runs are bitwise deterministic, so the duplicate writes
the same bytes it would have served anyway (the same argument that
makes the result cache sound).

**Ids.** Job/batch ids keep the PR 13 nonce prefix (``j<nonce>-<seq>``)
with a per-process nonce, so replicas can never mint colliding ids
without any coordination.

**Events.** Fleet members are a multi-process run WITHOUT a JAX
distributed launch, so each arms its own ``GS_EVENTS`` ``.rank<N>``
file (:func:`~..obs.events.arm_events`, ``GS_SERVE_FLEET_RANK``) and
the readers' existing ``rank_files`` merge (``gs_report``) tells one
fleet-wide story.

Stdlib-only and JAX-free to import, like the rest of ``serve/``.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from typing import Dict, List, Optional

from ..resilience.rendezvous import atomic_publish
from ..utils.log import Logger
from . import protocol
from .scheduler import (
    AdmissionError,
    Batch,
    Job,
    Scheduler,
    ServeConfig,
)

__all__ = ["ClusterScheduler", "FleetKV", "arm_fleet_events",
           "resolve_fleet_events_path", "worker_main"]


def resolve_fleet_events_path(cfg: ServeConfig) -> str:
    """This member's own event-stream file: the fleet's shared logical
    path (``GS_EVENTS``, defaulting to ``<fleet_dir>/events.jsonl``)
    suffixed ``.rank<fleet_rank>`` — the writer-side half of the
    multi-rank merge every reader already does."""
    from ..config.env import env_str

    base = env_str("GS_EVENTS", "")
    if base.endswith(f".rank{cfg.fleet_rank}"):
        # Already armed (idempotent re-entry).
        base = base[: -len(f".rank{cfg.fleet_rank}")]
    if not base:
        base = os.path.join(cfg.fleet_dir, "events.jsonl")
    return f"{base}.rank{cfg.fleet_rank}"


def arm_fleet_events(cfg: ServeConfig):
    """Point this process's event singleton at its own ``.rank<N>``
    file with the fleet rank as the ``proc`` id
    (:func:`~..obs.events.arm_events`)."""
    from ..obs import events as obs_events

    return obs_events.arm_events(
        resolve_fleet_events_path(cfg), proc=cfg.fleet_rank
    )


class FleetKV:
    """The shared-directory KV namespace (docstring above): atomic
    whole-document puts, torn-tolerant gets, exclusive claims and
    takes. Keys are ``/``-separated paths; every segment is a plain
    filename."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)

    def _path(self, key: str) -> str:
        return os.path.join(self.root, *key.split("/"))

    def put(self, key: str, doc: dict) -> None:
        """Last-writer-wins whole-document publish."""
        path = self._path(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        atomic_publish(path, json.dumps(doc, sort_keys=True))

    def get(self, key: str) -> Optional[dict]:
        """The document, or None (missing, or mid-replace — the next
        read sees it)."""
        try:
            with open(self._path(key), encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError):
            return None
        return doc if isinstance(doc, dict) else None

    def keys(self, prefix: str) -> List[str]:
        """Immediate child keys under ``prefix``, name-sorted (the
        queue's priority->FIFO order is encoded in the names)."""
        try:
            names = os.listdir(self._path(prefix))
        except OSError:
            return []
        return sorted(n for n in names if ".tmp." not in n)

    def claim(self, key: str, doc: dict) -> bool:
        """Create-exclusive: True for exactly one caller."""
        path = self._path(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            return False
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            f.write(json.dumps(doc, sort_keys=True))
        return True

    def take(self, src: str, dst: str) -> bool:
        """Atomically move ``src`` to ``dst``: True for exactly one
        caller (the losers' rename raises FileNotFoundError)."""
        dpath = self._path(dst)
        os.makedirs(os.path.dirname(dpath), exist_ok=True)
        try:
            os.rename(self._path(src), dpath)
        except OSError:
            return False
        return True

    def delete(self, key: str) -> None:
        try:
            os.remove(self._path(key))
        except OSError:
            pass


class _JobsView:
    """Duck-typed stand-in for the in-memory ``Scheduler.jobs`` dict:
    the HTTP handlers only ever call ``.get`` — here it reconstructs a
    fresh :class:`Job` from the shared job document, so ANY replica
    can answer status/result/SSE for a job another replica admitted."""

    def __init__(self, sched: "ClusterScheduler"):
        self._sched = sched

    def get(self, job_id: str) -> Optional[Job]:
        return self._sched._load_job(job_id)


class ClusterScheduler(Scheduler):
    """The fleet-wide scheduler: same interface as
    :class:`~.scheduler.Scheduler` (the HTTP handler and
    :class:`~.worker.WorkerFleet` cannot tell them apart), state in
    the shared :class:`FleetKV` instead of process memory."""

    def __init__(self, cfg: ServeConfig, *, role: str = "frontdoor",
                 events=None, metrics=None, log: Optional[Logger] = None):
        if not cfg.fleet_dir:
            raise ValueError(
                "ClusterScheduler needs GS_SERVE_FLEET_DIR (the shared "
                "fleet state directory)"
            )
        super().__init__(cfg, events=events, metrics=metrics)
        self.role = role
        self.log = log or Logger(verbose=False)
        self._kv = FleetKV(cfg.fleet_dir)
        self.member_id = (
            cfg.replica or f"{role}{cfg.fleet_rank}-{self._nonce}"
        )
        #: Batches THIS process launched and still leases.
        self._held: Dict[str, Batch] = {}
        self._member_doc = {
            "member": self.member_id, "role": role, "pid": os.getpid(),
            "host": socket.gethostname(), "port": None,
            "degraded": None,
            "t": time.time(),
        }
        self._kv.put(f"members/{self.member_id}", self._member_doc)
        self.events.emit(
            "worker_join", worker=self.member_id, role=role,
        )
        self.metrics.counter("serve_fleet_joins", role=role).inc()
        self._bg_stop = threading.Event()
        self._bg: List[threading.Thread] = []
        self._start_thread(self._heartbeat_loop, "gs-fleet-heartbeat")
        if role == "frontdoor":
            self._start_thread(self._reaper_loop, "gs-fleet-reaper")
        self.jobs = _JobsView(self)  # type: ignore[assignment]

    def _start_thread(self, target, name: str) -> None:
        t = threading.Thread(target=target, name=name, daemon=True)
        t.start()
        self._bg.append(t)

    # -------------------------------------------------------- documents

    def _write_job(self, job: Job, **extra) -> None:
        doc = {
            "job": job.id, "tenant": job.tenant,
            "spec": job.spec.describe(), "state": job.state,
            "seq": job.seq, "batch": job.batch_id, "slot": job.slot,
            "attempts": job.attempts, "error": job.error,
            "submitted_t": job.submitted_t, "packed_t": job.packed_t,
            "started_t": job.started_t,
            "first_step_t": job.first_step_t,
            "finished_t": job.finished_t, "store": job.store,
            "checkpoint_store": job.checkpoint_store,
            "digest": job.digest, "cache": job.cache,
            **extra,
        }
        self._kv.put(f"jobs/{job.id}", doc)

    def _load_job(self, job_id: str) -> Optional[Job]:
        doc = self._kv.get(f"jobs/{job_id}")
        if doc is None:
            return None
        try:
            spec = protocol.parse_job(
                doc["spec"], max_l=self.cfg.max_l,
                max_steps=self.cfg.max_steps,
            )
        except Exception:  # noqa: BLE001 — torn/foreign doc: not a job
            return None
        return Job(
            id=doc["job"], tenant=doc["tenant"], spec=spec,
            state=doc.get("state", "queued"), seq=doc.get("seq", 0),
            batch_id=doc.get("batch"), slot=doc.get("slot"),
            attempts=doc.get("attempts", 0), error=doc.get("error"),
            submitted_t=doc.get("submitted_t", 0.0),
            packed_t=doc.get("packed_t"),
            started_t=doc.get("started_t"),
            first_step_t=doc.get("first_step_t"),
            finished_t=doc.get("finished_t"),
            store=doc.get("store"),
            checkpoint_store=doc.get("checkpoint_store"),
            digest=doc.get("digest"), cache=doc.get("cache"),
        )

    # ----------------------------------------------------------- submit

    def submit(self, payload) -> Job:
        from . import cache as cache_mod

        spec = protocol.parse_job(
            payload, max_l=self.cfg.max_l, max_steps=self.cfg.max_steps
        )
        digest = cached = None
        if self.cache is not None:
            digest = cache_mod.job_digest(spec)
            cached = self.cache.lookup(digest)
        with self._cond:
            self._seq += 1
            seq = self._seq
        job = Job(
            id=f"j{self._nonce}-{seq:05d}", tenant=spec.tenant,
            spec=spec, seq=seq, submitted_t=time.time(), digest=digest,
        )
        if cached is not None and not self._closed:
            now = time.time()
            job.cache = "hit"
            job.state = "complete"
            job.store = cached["store"]
            job.first_step_t = job.finished_t = now
            self._write_job(job)
            self.metrics.counter("serve_cache_hits").inc()
            self.events.emit(
                "job_submitted", job=job.id, tenant=job.tenant,
                priority=spec.priority, model=spec.model, L=spec.L,
                steps=spec.steps, cache="hit",
            )
            self.events.emit(
                "cache_hit", digest=digest, job=job.id,
                tenant=job.tenant,
            )
            self.events.emit(
                "job_complete", job=job.id, tenant=job.tenant,
                status="complete", cache="hit",
                wall_s=round(now - job.submitted_t, 3),
            )
            return job
        reason = self._admission_reason(job)
        if reason is not None:
            job.state = "rejected"
            job.error = reason
            job.finished_t = time.time()
            self._write_job(job)
            self.metrics.counter(
                "serve_jobs_rejected", reason=reason
            ).inc()
            self.events.emit(
                "job_rejected", job=job.id, tenant=job.tenant,
                reason=reason,
            )
            raise AdmissionError(job, reason)
        # Queue marker name: priority digit (inverted so lexicographic
        # = highest first), then admission nanotime, then the id — the
        # fleet-wide analogue of the in-memory (-priority, seq) sort.
        qkey = (
            f"p{9 - spec.priority}-{time.time_ns():020d}-{job.id}"
        )
        if self.cache is not None:
            job.cache = "miss"
        self._write_job(job, qkey=qkey)
        self._kv.put(f"queue/{qkey}", {"job": job.id, "t": time.time()})
        self.metrics.counter("serve_jobs_submitted").inc()
        self.events.emit(
            "job_submitted", job=job.id, tenant=job.tenant,
            priority=spec.priority, model=spec.model, L=spec.L,
            steps=spec.steps,
        )
        if self.cache is not None:
            self.metrics.counter("serve_cache_misses").inc()
            self.events.emit(
                "cache_miss", digest=digest, job=job.id,
                tenant=job.tenant,
            )
        return job

    def _admission_reason(self, job: Job) -> Optional[str]:
        if self._closed:
            return "shutting_down"
        if len(self._kv.keys("queue")) >= self.cfg.queue_depth:
            return "queue_full"
        live = 0
        for jid in self._kv.keys("jobs"):
            doc = self._kv.get(f"jobs/{jid}")
            if (doc and doc.get("tenant") == job.tenant
                    and doc.get("state") in ("queued", "packed",
                                             "running")):
                live += 1
        if live >= self.cfg.tenant_quota:
            return "tenant_quota"
        return None

    # ----------------------------------------------------------- cancel

    def cancel(self, job_id: str) -> bool:
        doc = self._kv.get(f"jobs/{job_id}")
        if doc is None or doc.get("state") != "queued":
            return False
        qkey = doc.get("qkey")
        if not qkey or not self._kv.take(
            f"queue/{qkey}", f"cancelled/{qkey}"
        ):
            return False  # a worker won the marker: committed
        self._kv.delete(f"cancelled/{qkey}")
        job = self._load_job(job_id)
        if job is None:
            return False
        job.state = "cancelled"
        job.finished_t = time.time()
        self._write_job(job)
        self.events.emit(
            "job_complete", job=job.id, tenant=job.tenant,
            status="cancelled",
        )
        return True

    # ------------------------------------------------------------- pack

    def next_batch(self, timeout: float = 0.5) -> Optional[Batch]:
        deadline = time.monotonic() + max(timeout, 0.0)
        while True:
            batch = self._adopt_resume()
            if batch is not None:
                return batch
            batch = self._claim_fresh()
            if batch is not None:
                return batch
            if time.monotonic() >= deadline or self._closed:
                return None
            time.sleep(0.05)

    def _adopt_resume(self) -> Optional[Batch]:
        if self.degraded:
            return None
        for bid in self._kv.keys("resume"):
            doc = self._kv.get(f"resume/{bid}")
            if doc is None:
                continue
            if not self._kv.take(f"resume/{bid}", f"leases/{bid}"):
                continue  # another worker adopted it first
            # Exclusive owner now — overwrite the moved marker with a
            # real lease before anything else, so a crash right here
            # still expires into another failover.
            batch = self._rebuild_batch(doc)
            if batch is None:
                # Unreconstructable right now (torn docs mid-publish):
                # hand the entry back for a later retry.
                self._kv.take(f"leases/{bid}", f"resume/{bid}")
                continue
            self._lease(batch)
            return batch
        return None

    def mark_degraded(self, reason: str = "") -> None:
        """Cluster form: publish the degraded flag in this member's
        doc (the heartbeat keeps republishing it) and stop claiming
        work — healthy fleet peers drain the queue instead. Leased
        batches this member already holds stay leased; a crash expires
        them into the normal failover."""
        super().mark_degraded(reason)
        self._member_doc["degraded"] = self.degraded
        self._kv.put(f"members/{self.member_id}", self._member_doc)

    def _claim_fresh(self) -> Optional[Batch]:
        if self.degraded:
            # Suspect compute must not claim fresh work (or adopt a
            # peer's failover — next_batch checks there too): the
            # queue drains through healthy members.
            return None
        head_doc = head_qkey = None
        for qkey in self._kv.keys("queue"):
            marker = self._kv.get(f"queue/{qkey}")
            if marker is None:
                continue
            if self._kv.take(
                f"queue/{qkey}", f"claims/{self.member_id}/{qkey}"
            ):
                head_doc, head_qkey = marker, qkey
                break
        if head_doc is None:
            return None
        claimed = [(head_qkey, head_doc["job"])]
        head = self._load_job(head_doc["job"])
        if head is None:
            self._kv.delete(f"claims/{self.member_id}/{head_qkey}")
            return None
        key = protocol.pack_key(head.spec)
        window_end = time.monotonic() + self.cfg.pack_window_s
        while len(claimed) < self.cfg.pack_max:
            grabbed = False
            for qkey in self._kv.keys("queue"):
                if len(claimed) >= self.cfg.pack_max:
                    break
                marker = self._kv.get(f"queue/{qkey}")
                if marker is None:
                    continue
                job = self._load_job(marker["job"])
                if job is None or protocol.pack_key(job.spec) != key:
                    continue
                if self._kv.take(
                    f"queue/{qkey}", f"claims/{self.member_id}/{qkey}"
                ):
                    claimed.append((qkey, marker["job"]))
                    grabbed = True
            if len(claimed) >= self.cfg.pack_max:
                break
            remaining = window_end - time.monotonic()
            if remaining <= 0:
                break
            if not grabbed:
                time.sleep(min(0.05, remaining))
        jobs = [j for _, jid in claimed
                if (j := self._load_job(jid)) is not None]
        batch = self._build_cluster_batch(jobs, key)
        self._lease(batch)
        for qkey, _ in claimed:
            self._kv.delete(f"claims/{self.member_id}/{qkey}")
        return batch

    def _batch_dir(self, batch_id: str) -> str:
        return os.path.join(self._kv.root, "batches", batch_id)

    def _build_cluster_batch(self, jobs: List[Job], key) -> Batch:
        from ..ensemble.io import member_path
        from .scheduler import _pow2_slots

        with self._cond:
            self._batch_seq += 1
            seq = self._batch_seq
        batch_id = f"b{self._nonce}-{seq:04d}"
        n_slots = _pow2_slots(len(jobs), self.cfg.pack_max)
        bdir = self._batch_dir(batch_id)
        os.makedirs(bdir, exist_ok=True)
        settings = protocol.batch_settings(
            [j.spec for j in jobs], n_slots=n_slots,
            output=os.path.join(bdir, "gs.bp"),
            checkpoint_output=os.path.join(bdir, "ckpt.bp"),
            names=[j.id for j in jobs], supervise=self.cfg.supervise,
        )
        batch = Batch(
            id=batch_id, jobs=jobs, key=key, n_slots=n_slots,
            settings=settings, dir=bdir, supervise=self.cfg.supervise,
            created_t=time.time(),
        )
        now = time.time()
        for slot, job in enumerate(jobs):
            job.state = "packed"
            job.batch_id = batch_id
            job.slot = slot
            job.packed_t = now
            job.attempts += 1
            job.store = member_path(settings.output, slot, n_slots)
            if settings.checkpoint:
                job.checkpoint_store = member_path(
                    settings.checkpoint_output, slot, n_slots
                )
            self._write_job(job)
            self.events.emit(
                "job_packed", job=job.id, tenant=job.tenant,
                batch=batch_id, slot=slot, members=len(jobs),
                slots=n_slots,
            )
        self.metrics.histogram("serve_pack_members").observe(
            float(len(jobs))
        )
        return batch

    def _rebuild_batch(self, resume_doc: dict) -> Optional[Batch]:
        """A resume entry (another worker's failed lease) back into a
        launchable :class:`Batch` — spec truth comes from the shared
        job docs, the launch dir is the original one (shared FS), so
        the checkpoint-quorum resume path is exactly the in-process
        requeue."""
        jobs = [j for jid in resume_doc.get("jobs", [])
                if (j := self._load_job(jid)) is not None]
        if not jobs:
            return None
        try:
            settings = protocol.batch_settings(
                [j.spec for j in jobs],
                n_slots=int(resume_doc["n_slots"]),
                output=os.path.join(resume_doc["dir"], "gs.bp"),
                checkpoint_output=os.path.join(
                    resume_doc["dir"], "ckpt.bp"
                ),
                names=[j.id for j in jobs],
                supervise=self.cfg.supervise,
            )
        except Exception:  # noqa: BLE001 — torn docs
            return None
        return Batch(
            id=resume_doc["batch"], jobs=jobs,
            key=protocol.pack_key(jobs[0].spec),
            n_slots=int(resume_doc["n_slots"]), settings=settings,
            dir=resume_doc["dir"], supervise=self.cfg.supervise,
            attempt=int(resume_doc.get("attempt", 1)),
            created_t=time.time(),
        )

    def _lease(self, batch: Batch) -> None:
        self._held[batch.id] = batch
        self._kv.put(f"leases/{batch.id}", {
            "batch": batch.id, "worker": self.member_id,
            "jobs": batch.job_ids, "attempt": batch.attempt,
            "dir": batch.dir, "n_slots": batch.n_slots,
            "expires_t": time.time() + self.cfg.lease_ttl_s,
        })

    # ---------------------------------------------------------- requeue

    def requeue(self, batch: Batch, fault: str) -> None:
        batch.attempt += 1
        if getattr(batch.settings, "faults", ""):
            batch.settings.faults = ""
        for job in batch.jobs:
            job.state = "packed"
            job.attempts += 1
            self._write_job(job)
            self.events.emit(
                "job_requeued", job=job.id, tenant=job.tenant,
                batch=batch.id, fault=fault, attempt=batch.attempt,
            )
        self._held.pop(batch.id, None)
        self._kv.delete(f"leases/{batch.id}")
        # A pending reshape request targeted live state that no longer
        # exists; the relaunch restores from the checkpoint quorum on
        # whatever mesh it starts with (docs/RESHARD.md).
        self._kv.delete(f"reshape/{batch.id}")
        self._kv.put(f"resume/{batch.id}", {
            "batch": batch.id, "jobs": batch.job_ids,
            "attempt": batch.attempt, "dir": batch.dir,
            "n_slots": batch.n_slots,
        })
        self.metrics.counter(
            "serve_batches_requeued", fault=fault
        ).inc()

    # --------------------------------------------------------- complete

    def complete(self, batch: Batch, *, ok: bool,
                 error: Optional[str] = None,
                 wall_s: Optional[float] = None) -> None:
        now = time.time()
        for job in batch.jobs:
            job.state = "complete" if ok else "failed"
            job.error = None if ok else error
            job.finished_t = now
            if job.first_step_t is None and ok:
                job.first_step_t = now
            self._write_job(job)
            self.events.emit(
                "job_complete", job=job.id, tenant=job.tenant,
                batch=batch.id, status=job.state,
                wall_s=round(wall_s, 3) if wall_s is not None else None,
            )
            if ok and job.first_step_t is not None:
                self.metrics.histogram(
                    "serve_request_to_first_step_ms"
                ).observe((job.first_step_t - job.submitted_t) * 1e3)
        self._held.pop(batch.id, None)
        self._kv.delete(f"leases/{batch.id}")
        self._kv.delete(f"reshape/{batch.id}")
        self.metrics.counter(
            "serve_batches_complete", ok=str(ok).lower()
        ).inc()
        if ok and self.cache is not None:
            for job in batch.jobs:
                if job.store:
                    self.cache.publish(
                        job.spec, job.store, job=job.id,
                        digest=job.digest,
                    )

    # ----------------------------------------------------- run tracking

    def _on_event(self, record: dict) -> None:
        """Write the launch's progress through to the shared job docs
        (run_start -> running, first output/checkpoint -> first-step
        mark) for batches THIS process holds — other replicas read the
        docs, not this process's stream."""
        kind = record.get("kind")
        if kind not in ("run_start", "output", "checkpoint",
                        "run_complete"):
            return
        batch_id = (record.get("attrs") or {}).get("batch")
        if not batch_id:
            return
        batch = self._held.get(batch_id)
        if batch is None:
            return
        ts = record.get("ts") or time.time()
        for job in batch.jobs:
            if kind == "run_start" and job.state == "packed":
                job.state = "running"
                job.started_t = job.started_t or ts
                self._write_job(job)
            elif kind in ("output", "checkpoint", "run_complete"):
                if job.first_step_t is None and job.state in (
                    "packed", "running",
                ):
                    job.first_step_t = ts
                    self._write_job(job)

    # ------------------------------------------------------- background

    def _heartbeat_loop(self) -> None:
        """Every member: refresh the member doc; workers additionally
        renew their held leases — a live worker's lease never
        expires."""
        while not self._bg_stop.wait(self.cfg.heartbeat_s):
            self._member_doc["t"] = time.time()
            self._kv.put(
                f"members/{self.member_id}", self._member_doc
            )
            for batch in list(self._held.values()):
                lease = self._kv.get(f"leases/{batch.id}")
                if lease is None or lease.get("worker") != (
                    self.member_id
                ):
                    # Reaped out from under us (we stalled past the
                    # TTL): the batch now belongs to the fleet; let
                    # our duplicate run finish — deterministic bytes —
                    # but stop renewing.
                    self._held.pop(batch.id, None)
                    continue
                lease["expires_t"] = (
                    time.time() + self.cfg.lease_ttl_s
                )
                self._kv.put(f"leases/{batch.id}", lease)

    def _reaper_loop(self) -> None:
        """Front-door replicas: notice dead members (stale heartbeat),
        expired leases (dead worker mid-batch -> resume entry), and
        orphaned claims (dead worker between claim and lease ->
        re-enqueue). Every action is a take/claim — N replicas race,
        exactly one acts."""
        while not self._bg_stop.wait(self.cfg.heartbeat_s):
            now = time.time()
            try:
                self._reap_members(now)
                self._reap_leases(now)
                self._reap_claims(now)
            except Exception as e:  # noqa: BLE001 — reaper must survive
                self.log.warn(f"fleet reaper: {type(e).__name__}: {e}")

    def _reap_members(self, now: float) -> None:
        for mid in self._kv.keys("members"):
            doc = self._kv.get(f"members/{mid}")
            if doc is None or mid == self.member_id:
                continue
            if now - doc.get("t", 0) <= self.cfg.lease_ttl_s:
                continue
            if self._kv.take(f"members/{mid}", f"lost/{mid}"):
                self._kv.delete(f"lost/{mid}")
                self.events.emit("worker_lost", worker=mid)
                self.metrics.counter("serve_fleet_losses").inc()

    def _reap_leases(self, now: float) -> None:
        for bid in self._kv.keys("leases"):
            lease = self._kv.get(f"leases/{bid}")
            if lease is None or now <= lease.get("expires_t", 0):
                continue
            if not self._kv.take(
                f"leases/{bid}", f"reaped/{bid}"
            ):
                continue  # another replica noticed first
            self._kv.delete(f"reaped/{bid}")
            # Any in-flight reshape request dies with the worker: the
            # live state it addressed is gone, and the failover restore
            # is byte-identical without it.
            self._kv.delete(f"reshape/{bid}")
            attempt = int(lease.get("attempt", 0)) + 1
            dead_worker = lease.get("worker", "?")
            if attempt > self.cfg.max_requeues:
                # The batch has burned its fail-over budget: terminal.
                for jid in lease.get("jobs", []):
                    job = self._load_job(jid)
                    if job is None or job.state in (
                        "complete", "failed", "cancelled",
                    ):
                        continue
                    job.state = "failed"
                    job.error = (
                        f"worker {dead_worker} lost; requeue budget "
                        "exhausted"
                    )
                    job.finished_t = time.time()
                    self._write_job(job)
                    self.events.emit(
                        "job_complete", job=job.id, tenant=job.tenant,
                        batch=bid, status="failed",
                    )
                continue
            for jid in lease.get("jobs", []):
                job = self._load_job(jid)
                if job is None:
                    continue
                job.state = "packed"
                job.attempts += 1
                self._write_job(job)
                self.events.emit(
                    "job_failover", job=job.id, tenant=job.tenant,
                    batch=bid, worker=dead_worker,
                )
            self._kv.put(f"resume/{bid}", {
                "batch": bid, "jobs": lease.get("jobs", []),
                "attempt": attempt, "dir": lease.get("dir"),
                "n_slots": int(lease.get("n_slots", 1)),
            })
            self.metrics.counter("serve_fleet_failovers").inc()

    def _reap_claims(self, now: float) -> None:
        for mid in self._kv.keys("claims"):
            if self._kv.get(f"members/{mid}") is not None:
                continue  # claimant is alive; mid-pack is normal
            for qkey in self._kv.keys(f"claims/{mid}"):
                marker = self._kv.get(f"claims/{mid}/{qkey}")
                if marker is None:
                    continue
                if now - marker.get("t", now) <= self.cfg.lease_ttl_s:
                    continue
                if self._kv.take(
                    f"claims/{mid}/{qkey}", f"queue/{qkey}"
                ):
                    self.log.warn(
                        f"fleet reaper: re-enqueued orphaned claim "
                        f"{qkey} of dead member {mid}"
                    )

    # ---------------------------------------------------------- elastic

    def queue_depth(self) -> int:
        depth = len(self._kv.keys("queue"))
        self.metrics.gauge("serve_queue_depth").set(depth)
        return depth

    def running_batches(self) -> List[Batch]:
        """Every leased batch with a RUNNING member, fleet-wide: held
        launches directly, other members' through the shared job docs
        (a front-door elastic controller steers workers it never
        launched)."""
        out: List[Batch] = []
        for bid in self._kv.keys("leases"):
            lease = self._kv.get(f"leases/{bid}")
            if lease is None:
                continue
            held = self._held.get(bid)
            if held is not None:
                if any(j.state == "running" for j in held.jobs):
                    out.append(held)
                continue
            jobs = [j for jid in lease.get("jobs", [])
                    if (j := self._load_job(jid)) is not None]
            if not any(j.state == "running" for j in jobs):
                continue
            out.append(Batch(
                id=bid, jobs=jobs, key=(),
                n_slots=int(lease.get("n_slots", 1)), settings=None,
                dir=lease.get("dir") or "",
                created_t=float(
                    lease.get("expires_t", time.time())
                ) - self.cfg.lease_ttl_s,
            ))
        return out

    def request_reshape(self, batch_id: str, req: dict) -> bool:
        """Publish the request as a ``reshape/<batch>`` KV doc; the
        LEASING member's worker consumes it (:meth:`take_reshape`)
        at its next between-rounds poll — the relay that lets any
        replica steer any worker's live mesh. Latest-wins."""
        if self._kv.get(f"leases/{batch_id}") is None:
            return False
        self._kv.put(f"reshape/{batch_id}", {
            "batch": batch_id, "req": dict(req),
            "by": self.member_id, "t": time.time(),
        })
        return True

    def take_reshape(self, batch_id: str) -> Optional[dict]:
        taken = f"reshape-taken/{self.member_id}-{batch_id}"
        if not self._kv.take(f"reshape/{batch_id}", taken):
            return None
        doc = self._kv.get(taken)
        self._kv.delete(taken)
        req = (doc or {}).get("req")
        return req if isinstance(req, dict) else None

    # ----------------------------------------------------------- status

    def announce_endpoint(self, host: str, port: int) -> None:
        """Record the bound HTTP endpoint in the member doc — how
        launchers and tests discover a replica's ephemeral port."""
        self._member_doc["host"] = host
        self._member_doc["port"] = int(port)
        self._kv.put(f"members/{self.member_id}", self._member_doc)

    def status(self, job_id: str) -> Optional[dict]:
        job = self._load_job(job_id)
        return None if job is None else job.describe()

    def idle(self) -> bool:
        if (self._kv.keys("queue") or self._kv.keys("resume")
                or self._kv.keys("leases")):
            return False
        return not any(
            self._kv.keys(f"claims/{m}")
            for m in self._kv.keys("claims")
        )

    def describe(self) -> dict:
        members = {}
        for mid in self._kv.keys("members"):
            doc = self._kv.get(f"members/{mid}")
            if doc:
                members[mid] = {
                    "role": doc.get("role"), "port": doc.get("port"),
                }
        return {
            "member": self.member_id,
            "role": self.role,
            "queued": len(self._kv.keys("queue")),
            "resume_batches": len(self._kv.keys("resume")),
            "leases": len(self._kv.keys("leases")),
            "members": members,
            "config": self.cfg.describe(),
        }

    def close(self) -> None:
        self.drain()
        self._bg_stop.set()
        for t in self._bg:
            t.join(self.cfg.heartbeat_s + 1.0)
        self._kv.delete(f"members/{self.member_id}")
        self.detach_events()


def worker_main(argv=None) -> int:
    """Entry point for a pure fleet worker process (``gs_serve.py
    --role worker``): no HTTP server — just a :class:`ClusterScheduler`
    in worker role and a :class:`~.worker.WorkerFleet` draining the
    shared queue until SIGTERM/SIGINT."""
    import signal

    from .scheduler import resolve_serve_config
    from .worker import WorkerFleet

    cfg = resolve_serve_config()
    if not cfg.fleet_dir:
        raise SystemExit(
            "gs-serve worker role needs GS_SERVE_FLEET_DIR"
        )
    if cfg.workers < 1:
        raise SystemExit(
            "gs-serve worker role needs GS_SERVE_WORKERS >= 1"
        )
    arm_fleet_events(cfg)
    log = Logger(verbose=True)
    sched = ClusterScheduler(cfg, role="worker", log=log)
    sched.attach_events()
    fleet = WorkerFleet(sched, cfg, log=log)
    from .elastic import ElasticController

    elastic = ElasticController(sched, fleet, log=log)
    stop = threading.Event()

    def _request_stop(signum, frame):  # noqa: ARG001
        stop.set()

    signal.signal(signal.SIGTERM, _request_stop)
    signal.signal(signal.SIGINT, _request_stop)
    fleet.start()
    elastic.start()
    log.info(
        f"gs-serve worker {sched.member_id}: draining fleet "
        f"{cfg.fleet_dir} ({cfg.workers} thread(s))"
    )
    try:
        while not stop.is_set():
            stop.wait(0.5)
    finally:
        elastic.close()
        fleet.stop()
        sched.close()
        log.info(f"gs-serve worker {sched.member_id}: bye")
    return 0
