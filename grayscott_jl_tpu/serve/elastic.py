"""Elastic capacity control: queue pressure -> live mesh reshapes.

The missing half of the serve control loop (ROADMAP item 5): the
scheduler already *measures* load (the ``serve_queue_depth`` gauge,
worker utilization) but nothing *acted* on it — a deep queue just sat
behind whatever mesh each running batch happened to launch on. The
:class:`ElasticController` closes the loop: a daemon thread samples
both signals every tick and, through the scheduler's
``request_reshape`` seam, posts live grow/shrink requests that the
worker's between-rounds ``reshape_poll`` hook turns into
:func:`~..reshard.restore.reshape_live` moves — no kill, no
checkpoint round-trip, continuation bitwise-identical
(docs/RESHARD.md "In-job reshapes").

The policy is deliberately boring — hysteresis plus cooldown:

* **pressure** (queue depth >= ``GS_SERVE_ELASTIC_HIGH`` *and* every
  worker busy) sustained for ``GS_SERVE_ELASTIC_SUSTAIN`` consecutive
  ticks -> SHRINK one running batch's spatial mesh (halve its device
  footprint), freeing devices for the queued work;
* **relief** (queue depth <= ``GS_SERVE_ELASTIC_LOW`` *and* spare
  worker capacity) sustained the same way -> GROW one running batch
  (double its footprint), spending the idle devices on finishing
  sooner;
* any action arms a ``GS_SERVE_ELASTIC_COOLDOWN_S`` refractory window
  so the controller cannot thrash a batch through
  grow/shrink/grow cycles faster than the reshapes themselves settle.

The controller posts *scale hints* (``{"scale": "grow"|"shrink"}``),
never concrete meshes: the driver owns feasibility (device inventory,
divisibility, the per-axis halo floor — ``_resolve_reshape_dims``)
because only the process holding the live simulation knows them. An
infeasible hint degrades to a no-op there, loudly in the log.

Off by default (``GS_SERVE_ELASTIC=1`` opts in); stdlib-only and
JAX-free to import, like the rest of ``serve/``. Every action lands
on the unified event stream as an ``elastic`` record (schema in
``scripts/gs_report.py``) plus the ``serve_elastic_actions`` counter.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Optional

from ..config.env import env_flag, env_float, env_int
from ..utils.log import Logger

__all__ = [
    "ElasticConfig",
    "ElasticController",
    "resolve_elastic_config",
]


@dataclasses.dataclass
class ElasticConfig:
    """Resolved ``GS_SERVE_ELASTIC*`` knob family (docs/SERVICE.md)."""

    enabled: bool = False
    high: int = 4
    low: int = 0
    sustain: int = 2
    cooldown_s: float = 5.0
    tick_s: float = 0.5

    def describe(self) -> dict:
        return dataclasses.asdict(self)


def resolve_elastic_config(settings=None) -> ElasticConfig:
    """The ``GS_SERVE_ELASTIC*`` env knobs -> :class:`ElasticConfig`.

    Env-only, like :func:`~.scheduler.resolve_serve_config` (the
    service is launched by ``scripts/gs_serve.py``, not a TOML table).
    """
    cfg = ElasticConfig(
        enabled=env_flag("GS_SERVE_ELASTIC", False),
        high=env_int("GS_SERVE_ELASTIC_HIGH", 4),
        low=env_int("GS_SERVE_ELASTIC_LOW", 0),
        sustain=env_int("GS_SERVE_ELASTIC_SUSTAIN", 2),
        cooldown_s=env_float("GS_SERVE_ELASTIC_COOLDOWN_S", 5.0),
        tick_s=env_float("GS_SERVE_ELASTIC_TICK_S", 0.5),
    )
    if cfg.high < 1:
        raise ValueError(
            f"GS_SERVE_ELASTIC_HIGH must be >= 1, got {cfg.high}"
        )
    if not 0 <= cfg.low < cfg.high:
        raise ValueError(
            f"GS_SERVE_ELASTIC_LOW must be in [0, high={cfg.high}), "
            f"got {cfg.low} — overlapping thresholds defeat the "
            "hysteresis"
        )
    if cfg.sustain < 1:
        raise ValueError(
            f"GS_SERVE_ELASTIC_SUSTAIN must be >= 1, got {cfg.sustain}"
        )
    if cfg.cooldown_s < 0:
        raise ValueError(
            f"GS_SERVE_ELASTIC_COOLDOWN_S must be >= 0, got "
            f"{cfg.cooldown_s}"
        )
    if cfg.tick_s <= 0:
        raise ValueError(
            f"GS_SERVE_ELASTIC_TICK_S must be > 0, got {cfg.tick_s}"
        )
    return cfg


class ElasticController:
    """One daemon thread turning load signals into reshape requests.

    ``fleet`` is anything with a ``utilization() -> float`` (the local
    :class:`~.worker.WorkerFleet`); pass None on a pure front door —
    utilization then reads as fully busy, so only the queue signal
    drives the policy (a front door can still post requests that
    fleet workers consume through the cluster KV relay).
    """

    def __init__(self, scheduler, fleet=None,
                 cfg: Optional[ElasticConfig] = None, *,
                 events=None, metrics=None,
                 log: Optional[Logger] = None):
        self.scheduler = scheduler
        self.fleet = fleet
        self.cfg = cfg or resolve_elastic_config()
        if events is None:
            from ..obs import events as obs_events

            events = obs_events.get_events()
        if metrics is None:
            from ..obs import metrics as obs_metrics

            metrics = obs_metrics.get_metrics()
        self.events = events
        self.metrics = metrics
        self.log = log or Logger(verbose=False)
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._pressure_ticks = 0
        self._relief_ticks = 0
        self._cooldown_until = 0.0
        self.actions = 0

    # ------------------------------------------------------- lifecycle

    def start(self) -> "ElasticController":
        """No-op unless ``GS_SERVE_ELASTIC=1``; idempotent."""
        if self.cfg.enabled and self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="gs-serve-elastic", daemon=True
            )
            self._thread.start()
        return self

    def close(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None

    # ------------------------------------------------------------ loop

    def _run(self) -> None:
        while not self._stop.wait(self.cfg.tick_s):
            try:
                self.tick()
            except Exception as e:  # pragma: no cover — keep sampling
                self.log.warn(f"elastic tick failed: {e}")

    def tick(self) -> Optional[str]:
        """One policy evaluation; returns the action taken (for
        tests), else None. Split from the thread loop so tests can
        drive the policy deterministically without sleeping."""
        depth = self.scheduler.queue_depth()
        util = (
            self.fleet.utilization() if self.fleet is not None else 1.0
        )
        pressure = depth >= self.cfg.high and util >= 1.0
        relief = depth <= self.cfg.low and util < 1.0
        self._pressure_ticks = (
            self._pressure_ticks + 1 if pressure else 0
        )
        self._relief_ticks = self._relief_ticks + 1 if relief else 0
        if time.monotonic() < self._cooldown_until:
            return None
        if pressure and self._pressure_ticks >= self.cfg.sustain:
            return self._act("shrink", depth, util)
        if relief and self._relief_ticks >= self.cfg.sustain:
            return self._act("grow", depth, util)
        return None

    def _act(self, scale: str, depth: int,
             util: float) -> Optional[str]:
        running = self.scheduler.running_batches()
        if not running:
            return None
        # Oldest running batch first: it has the most remaining value
        # from a grow and the most settled compile state to shrink.
        batch = min(running, key=lambda b: b.created_t)
        if not self.scheduler.request_reshape(
            batch.id, {"scale": scale}
        ):
            return None
        self.actions += 1
        self._pressure_ticks = self._relief_ticks = 0
        self._cooldown_until = time.monotonic() + self.cfg.cooldown_s
        self.metrics.counter(
            "serve_elastic_actions", action=scale
        ).inc()
        self.events.emit(
            "elastic", action=scale, batch=batch.id, depth=depth,
            utilization=round(util, 3),
        )
        self.log.info(
            f"elastic: {scale} {batch.id} "
            f"(depth={depth}, util={util:.2f})"
        )
        return scale
