"""Content-addressed result cache: a JobSpec digest IS its trajectory.

The framework's strongest property is that runs are bitwise
deterministic — a packed member's store is byte-identical to the solo
run of the same spec (docs/SERVICE.md, "equality fine print"), across
restarts, requeues, and pack factors. So a finished trajectory is
fully determined by the physics-relevant spec fields, and a repeated
request is a store READ, not a launch (ROADMAP item 4; the
workflow-composition move of arxiv 2309.10292 applied to the service
layer).

:func:`canonical_spec` fixes the identity: model, resolved member
parameters (defaults filled, canonically ordered, floats spelled as
``float.hex()`` so ``0.06`` and ``0.060`` collide and no decimal
formatting ambiguity separates equal values), seed, L, steps, the
output/checkpoint cadence (they shape WHICH steps the store holds),
precision + the resolved compute-precision posture, halo_depth, and
the snapshot-codec posture (lossy bytes differ from exact bytes).
Deliberately EXCLUDED: tenant and priority — they shape scheduling,
not bytes, and the whole point is that different users hit the same
entry.

:class:`ResultCache` maps ``digest -> finished store`` through the
shared filesystem:

* **publish** (worker side, batch completion) records the entry with
  :func:`~..resilience.rendezvous.atomic_publish` (last-writer-wins is
  safe: every writer of a digest holds identical bytes) and mirrors
  the store per ``GS_CKPT_REPLICAS``
  (:func:`~..resilience.integrity.replicate_store`) for durability;
* **lookup** (front-door side, admission) re-verifies the artifact's
  PR 14 CRC sidecars (:func:`~..resilience.integrity.verify_store`)
  before vouching for it, failing over to an on-disk mirror when the
  primary rots, and degrading to a cache MISS — a fresh launch — when
  every copy is corrupt. A bad byte is never served; at worst a hit
  becomes a recompute.

Stdlib-only and JAX-free to import, like the rest of ``serve/``.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from types import SimpleNamespace
from typing import Optional

from ..config.env import env_flag, env_str
from ..config.settings import resolve_compute_precision
from ..io.codec import resolve_snapshot_codec
from ..models import get_model
from ..resilience.integrity import (
    CorruptionError,
    _existing_replicas,
    replicate_store,
    verify_store,
)
from . import protocol

__all__ = [
    "ResultCache",
    "canonical_spec",
    "job_digest",
    "resolve_cache_dir",
    "resolve_cache_enabled",
    "resolve_cache_verify",
]


def resolve_cache_enabled() -> bool:
    """``GS_SERVE_CACHE`` — serve the result cache (default on; the
    determinism contract makes it safe by construction)."""
    return env_flag("GS_SERVE_CACHE", True)


def resolve_cache_dir(default: str = "") -> str:
    """``GS_CACHE_DIR`` — the cache root; empty defers to the
    scheduler's default (``<state_dir>/cache``, or the shared
    ``<fleet_dir>/cache`` for fleet members)."""
    return env_str("GS_CACHE_DIR", default)


def resolve_cache_verify() -> bool:
    """``GS_CACHE_VERIFY`` — CRC-verify cached artifacts at lookup
    time (default on). Off trusts publish-time CRCs; the read gate is
    what turns silent disk rot into a failover instead of a bad
    payload, so leave it on outside benchmarks."""
    return env_flag("GS_CACHE_VERIFY", True)


def canonical_spec(spec: protocol.JobSpec) -> dict:
    """The physics-identity document of one job — every field that
    determines the finished store's bytes, spelled canonically.

    Floats go through ``float.hex()``: exact, round-trippable, and
    formatting-independent — ``1e-2`` and ``0.01`` collide, while any
    value delta (even one ulp) separates. Parameters come
    default-filled and canonically ordered from
    :func:`~.protocol.resolved_params`, so ``{"f": 0.03}`` and
    ``{"f": 0.03, "k": <default k>}`` are the same scenario here just
    as they are on the device. Postures resolve through the SAME
    resolvers the worker's launch uses (``resolve_compute_precision``,
    ``resolve_snapshot_codec``), so the digest names the bytes this
    environment would actually write.
    """
    model = get_model(spec.model)
    stub = SimpleNamespace(compute_precision="", precision=spec.precision)
    return {
        "v": 1,
        "model": spec.model,
        "L": spec.L,
        "steps": spec.steps,
        "plotgap": spec.plotgap,
        "checkpoint_freq": spec.checkpoint_freq,
        "precision": spec.precision,
        "halo_depth": spec.halo_depth,
        "seed": spec.seed,
        "params": [
            [name, float(value).hex()]
            for name, value in protocol.resolved_params(spec)
        ],
        "compute_precision": resolve_compute_precision(stub),
        "snapshot_codec": resolve_snapshot_codec(
            stub, model.field_names
        ).posture(),
    }


def job_digest(spec: protocol.JobSpec) -> str:
    """sha256 of the canonical spec document (sorted keys, no
    whitespace) — the cache key."""
    blob = json.dumps(
        canonical_spec(spec), sort_keys=True, separators=(",", ":")
    )
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()


class ResultCache:
    """``digest -> finished member store`` over a (possibly shared)
    directory tree.

    Entries live at ``<root>/<digest[:2]>/<digest>.json`` — two-level
    fan-out so a planet-scale cache directory never holds millions of
    siblings — and are published atomically, so a concurrent reader
    sees a complete entry or none. ``verifier`` is injectable for unit
    tests (defaults to the PR 14 CRC audit
    :func:`~..resilience.integrity.verify_store`).
    """

    def __init__(self, root: str, *, events=None, metrics=None,
                 verify: bool = True, verifier=None):
        self.root = root
        if events is None:
            from ..obs import events as obs_events

            events = obs_events.get_events()
        if metrics is None:
            from ..obs import metrics as obs_metrics

            metrics = obs_metrics.get_metrics()
        self.events = events
        self.metrics = metrics
        self.verify = bool(verify)
        self._verifier = verifier if verifier is not None else verify_store
        os.makedirs(root, exist_ok=True)

    # --------------------------------------------------------- entries

    def entry_path(self, digest: str) -> str:
        return os.path.join(self.root, digest[:2], f"{digest}.json")

    def _read_entry(self, digest: str) -> Optional[dict]:
        try:
            with open(self.entry_path(digest), encoding="utf-8") as f:
                entry = json.load(f)
        except (OSError, json.JSONDecodeError):
            return None
        return entry if isinstance(entry, dict) else None

    # --------------------------------------------------------- publish

    def publish(self, spec: protocol.JobSpec, store: str, *,
                job: str = "", digest: Optional[str] = None
                ) -> Optional[dict]:
        """Record ``digest -> store`` after a batch finishes (worker /
        completing-scheduler side). Verifies the artifact BEFORE
        vouching for it (a store that already fails its own CRCs must
        not become a cache entry), then mirrors it per
        ``GS_CKPT_REPLICAS`` and writes the entry atomically.
        Idempotent and race-safe: every publisher of a digest holds
        byte-identical stores, so last-writer-wins is a no-op. Returns
        the entry, or None when the store is unpublishable (missing,
        no committed metadata, or corrupt)."""
        if digest is None:
            digest = job_digest(spec)
        if not store or not os.path.isdir(store):
            return None
        try:
            report = self._verifier(store)
        except CorruptionError:
            return None
        entry = {
            "digest": digest,
            "store": store,
            "job": job,
            "steps_audited": report["steps_audited"],
            "published_t": round(time.time(), 6),
        }
        from ..resilience.rendezvous import atomic_publish

        path = self.entry_path(digest)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        mirrors = replicate_store(store)
        atomic_publish(path, json.dumps(entry, sort_keys=True))
        self.metrics.counter("serve_cache_published").inc()
        self.events.emit(
            "cache_publish", digest=digest, job=job, store=store,
            mirrors=len(mirrors),
        )
        return entry

    # ---------------------------------------------------------- lookup

    def lookup(self, digest: str) -> Optional[dict]:
        """The verified entry for ``digest``, or None (a miss).

        Health-ordered read gate: try the recorded primary store, then
        every on-disk ``.r<k>`` mirror, returning the FIRST candidate
        that passes the CRC audit (the entry's ``store`` field is
        rewritten to the winning candidate). When every copy is
        corrupt the entry is dropped — the next publish of this digest
        rebuilds it from a fresh launch — and the lookup degrades to a
        miss. Never returns an unverified store while ``verify`` is
        on."""
        entry = self._read_entry(digest)
        if entry is None:
            return None
        store = entry.get("store")
        if not store or not os.path.isdir(store):
            self._drop(digest, reason="store_missing")
            return None
        if not self.verify:
            return entry
        candidates = [store] + _existing_replicas(store)
        for candidate in candidates:
            try:
                self._verifier(candidate)
            except CorruptionError:
                continue
            if candidate != store:
                self.metrics.counter(
                    "serve_cache_failover"
                ).inc()
            return {**entry, "store": candidate}
        self._drop(digest, reason="all_replicas_corrupt")
        return None

    def _drop(self, digest: str, *, reason: str) -> None:
        """Retire an entry that can no longer be served (primary and
        every mirror corrupt or gone). Dropping is what converts "bad
        cache" into "cache miss" — the caller launches fresh."""
        try:
            os.remove(self.entry_path(digest))
        except OSError:
            pass
        self.metrics.counter(
            "serve_cache_dropped", reason=reason
        ).inc()

    # -------------------------------------------------------- describe

    def describe(self) -> dict:
        entries = 0
        if os.path.isdir(self.root):
            for shard in os.listdir(self.root):
                sub = os.path.join(self.root, shard)
                if os.path.isdir(sub):
                    entries += sum(
                        1 for n in os.listdir(sub)
                        if n.endswith(".json")
                    )
        return {"root": self.root, "entries": entries,
                "verify": self.verify}
