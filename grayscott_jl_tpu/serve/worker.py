"""The serve worker fleet: batches in, supervised launches out.

Each worker is a thread looping ``scheduler.next_batch`` ->
``driver.run_once`` (under ``resilience.supervisor.supervise`` when
the batch is supervised — in-place restart of classified transient
failures, exactly the solo CLI's resilience story). Two serve-specific
pieces live here:

**Warm ensembles.** Member parameters, seeds, and PRNG keys are
runtime *inputs* of the compiled ensemble program, so a worker keeps
one :class:`~..ensemble.engine.EnsembleSimulation` per executable
shape (model x L x slots x precision x schedule — :func:`warm_key`)
and rebinds it to each new batch via ``repack`` — the second batch of
a shape pays ZERO recompilation. This is why the scheduler pads
batches to canonical power-of-two slot counts. The cache is
per-worker: compiled engines are never shared across threads.

**Requeue on worker death.** A launch failure that escapes supervision
(or a kill of the unsupervised kind — ``GS_SERVE_CHAOS`` models it) is
classified with the supervisor's taxonomy and handed BACK to the
scheduler as a batch-granular requeue: the relaunching worker resumes
every member from the member-store checkpoint quorum
(``ensemble/io.restore_ensemble`` + ``reshard/plan``; layout-agnostic,
so the resuming worker may sit on a different slice shape), or from
scratch when nothing durable exists yet — the member stores finish
byte-identical to an uninterrupted run either way (asserted in tier-1
and chaos_smoke scenario 6). Detected silent corruption rides the
same taxonomy (``corruption``, docs/RESILIENCE.md "Data integrity"):
a requeued batch's member restore goes through the replica-failover
read path, so a corrupt member checkpoint costs a ``replica_failover``
event, not a wrong answer served to a tenant.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Tuple

from ..utils.log import Logger
from .scheduler import Batch, Scheduler, ServeConfig

__all__ = ["WorkerFleet", "warm_key"]


def warm_key(settings) -> Tuple:
    """The executable-shape signature a warm engine can be rebound
    across (everything ``EnsembleSimulation.repack`` refuses to
    change): model, L, slot count, member sharding, precision, the
    halo/overlap schedule, and whether noise is traced."""
    ens = settings.ensemble
    return (
        ens.model,
        settings.L,
        ens.n,
        ens.member_shards,
        settings.precision,
        settings.kernel_language,
        settings.halo_depth,
        settings.comm_overlap,
        any(m.value("noise") != 0.0 for m in ens.members),
    )


class WorkerFleet:
    """``cfg.workers`` threads draining one :class:`Scheduler`."""

    def __init__(self, scheduler: Scheduler, cfg: ServeConfig,
                 *, log: Optional[Logger] = None):
        self.scheduler = scheduler
        self.cfg = cfg
        self.log = log or Logger(verbose=False)
        self._threads: list = []
        self._stop = threading.Event()
        # Per-worker warm engine cache; a compiled engine belongs to
        # exactly one thread for its whole life.
        self._warm: Dict[int, Dict[Tuple, object]] = {}
        self._busy: Dict[int, bool] = {}
        self.launches = 0
        self.warm_hits = 0

    # ---------------------------------------------------------- lifecycle

    def start(self) -> "WorkerFleet":
        for i in range(self.cfg.workers):
            t = threading.Thread(
                target=self._run, args=(i,),
                name=f"gs-serve-worker-{i}", daemon=True,
            )
            t.start()
            self._threads.append(t)
        return self

    def stop(self, timeout: float = 30.0) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout)
        self._threads = []

    # ------------------------------------------------------------- loop

    def _run(self, worker_id: int) -> None:
        while not self._stop.is_set():
            batch = self.scheduler.next_batch(timeout=0.2)
            if batch is None:
                continue
            self._busy[worker_id] = True
            try:
                self._launch(worker_id, batch)
            finally:
                self._busy[worker_id] = False

    def utilization(self) -> float:
        """Fraction of worker threads currently inside a launch — one
        of the two signals the elastic policy (``serve/elastic.py``)
        trades off against queue depth."""
        n = len(self._threads)
        if n == 0:
            return 0.0
        return sum(1 for b in self._busy.values() if b) / n

    def _factory(self, worker_id: int, batch: Batch):
        """The driver's ``sim_factory`` seam: hand back a warm engine
        rebound to this batch when the shape matches, else compile a
        fresh one and keep it warm."""

        def factory(settings, *, n_devices=None, seed: int = 0):
            from ..ensemble.engine import EnsembleSimulation

            cache = self._warm.setdefault(worker_id, {})
            key = warm_key(settings)
            sim = cache.get(key)
            if sim is not None:
                try:
                    sim.repack(settings, seed=seed)
                    batch.warm = True
                    self.warm_hits += 1
                    return sim
                except ValueError:
                    # Shape drifted out from under the key (should not
                    # happen — the key covers repack's refusals); fall
                    # through to a fresh compile.
                    cache.pop(key, None)
            sim = EnsembleSimulation(
                settings, n_devices=n_devices, seed=seed
            )
            cache[key] = sim
            return sim

        return factory

    def _launch(self, worker_id: int, batch: Batch) -> None:
        from ..obs import events as obs_events
        from ..resilience.supervisor import (
            classify_failure,
            latest_durable_checkpoint,
        )

        settings = batch.settings
        if batch.attempt > 0:
            # Requeued batch: resume from the member-store checkpoint
            # quorum when one exists (restore_ensemble rolls every
            # member back to the last step ALL of them hold durably,
            # idle pack slots re-initialize); a batch that never
            # checkpointed replays from scratch — deterministic, so the
            # stores come out identical either way.
            resume = (
                latest_durable_checkpoint(settings)
                if settings.checkpoint else None
            )
            if resume is not None:
                settings.restart = True
                settings.restart_input = settings.checkpoint_output
                settings.restart_step = -1
            else:
                settings.restart = False
        self.launches += 1
        t0 = time.time()
        member = getattr(self.scheduler, "member_id", "") or "local"
        try:
            # Every event the launch emits from this thread (driver
            # lifecycle, journal mirrors) carries the batch id — the
            # scheduler's progress tracker and the SSE fan-out key on
            # it (obs/events.bound) — plus the launching worker's
            # fleet identity, so a merged multi-rank report can
            # attribute every run event to the process that ran it.
            # Between-rounds elastic hook: the driver polls this
            # closure each step round; a posted reshape request
            # (Scheduler.take_reshape, consume-once) moves the live
            # ensemble onto the target mesh with no checkpoint
            # round-trip (docs/RESHARD.md "In-job reshapes").
            def reshape_poll(batch_id=batch.id):
                return self.scheduler.take_reshape(batch_id)

            with obs_events.bound(batch=batch.id,
                                  worker=f"{member}.{worker_id}"):
                if batch.supervise:
                    from ..resilience.supervisor import supervise

                    supervise(
                        settings, seed=0,
                        sim_factory=self._factory(worker_id, batch),
                        reshape_poll=reshape_poll,
                    )
                else:
                    from ..driver import run_once

                    run_once(
                        settings, seed=0,
                        sim_factory=self._factory(worker_id, batch),
                        reshape_poll=reshape_poll,
                    )
        except BaseException as exc:  # noqa: BLE001 — classified below
            kind = classify_failure(exc)
            label = kind or f"fatal:{type(exc).__name__}"
            if kind == "sdc":
                # Compute-path corruption that escaped the supervisor's
                # in-place recovery (or ran unsupervised): this
                # process's devices are suspect. Mark the member
                # degraded BEFORE requeueing so the batch lands on a
                # healthy fleet peer, not straight back here.
                self.scheduler.mark_degraded(
                    f"sdc: {type(exc).__name__}: {exc}"
                )
            if kind is not None and batch.attempt < (
                self.cfg.max_requeues
            ):
                self.log.warn(
                    f"serve worker {worker_id}: batch {batch.id} died "
                    f"({label}); requeueing "
                    f"(attempt {batch.attempt + 1})"
                )
                self.scheduler.requeue(batch, fault=label)
                return
            self.log.warn(
                f"serve worker {worker_id}: batch {batch.id} FAILED "
                f"({type(exc).__name__}: {exc})"
            )
            self.scheduler.complete(
                batch, ok=False,
                error=f"{type(exc).__name__}: {exc}",
                wall_s=time.time() - t0,
            )
            return
        self.scheduler.complete(
            batch, ok=True, wall_s=time.time() - t0
        )
