"""The HTTP front door: submit / status / cancel / result / SSE.

Stdlib ``http.server`` (threading) — the service has no new
dependencies, like everything else in the tree. Endpoints
(docs/SERVICE.md):

* ``POST /v1/jobs`` — submit a JSON job spec. 200 -> the queued job
  record; 400 -> a ``SettingsError`` text naming the spec problem
  (misspelled parameter, unknown model, oversized L); 429 -> admission
  refused (full queue / tenant quota), body names the reason.
* ``GET /v1/jobs/<id>`` — lifecycle record (state, batch, slot,
  timestamps, request-to-first-step latency once known).
* ``POST /v1/jobs/<id>/cancel`` — cancel a QUEUED job (409 once it is
  committed to a launch).
* ``GET /v1/jobs/<id>/result`` — terminal record + member store path
  (409 until terminal).
* ``GET /v1/jobs/<id>/field?field=u&z=8`` — one z-plane of a field
  from the job's member store (the latest durable output step):
  clients peek at a running simulation without any new I/O path —
  member stores ARE solo stores.
* ``GET /v1/jobs/<id>/events`` — server-sent events: the job's
  lifecycle + its batch's run events, fanned out live from the
  unified GS_EVENTS stream (``obs/events.subscribe``; no second
  telemetry path), with a compact field slice attached to each output
  boundary. Ends with a terminal frame when the job completes.
* ``GET /v1/healthz`` — liveness + scheduler counters.

The server owns process lifecycle: :class:`ServeService` arms the
event stream (the SSE fan-out and the scheduler's progress tracking
require one), builds the scheduler + worker fleet, and tears all of it
down in order on ``close()``.
"""

from __future__ import annotations

import json
import os
import queue
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

from ..models.base import SettingsError
from ..utils.log import Logger
from .scheduler import AdmissionError, Scheduler, ServeConfig
from .worker import WorkerFleet

__all__ = ["ServeService", "main"]


def _ensure_events(state_dir: str):
    """The service REQUIRES a live event stream (SSE fan-out, progress
    tracking, the job_* audit trail). Honor an operator-armed
    ``GS_EVENTS``; otherwise arm the stream at the state dir's
    ``events.jsonl`` before the process-wide singleton resolves."""
    from ..obs import events as obs_events

    stream = obs_events.get_events()
    if stream.enabled:
        return stream
    os.makedirs(state_dir, exist_ok=True)
    os.environ["GS_EVENTS"] = os.path.join(state_dir, "events.jsonl")
    obs_events.reset_events()
    return obs_events.get_events()


def _field_slice(job, *, field: Optional[str] = None,
                 z: Optional[int] = None, stride: int = 1) -> dict:
    """One z-plane of one field from the job's member OUTPUT store at
    its latest durable step — read through the standard BP-lite reader
    (durability rules included: a torn tail is invisible)."""
    from ..io.bplite import BpReader

    if job.store is None or not os.path.exists(job.store):
        raise FileNotFoundError("no output store yet")
    L = job.spec.L
    z = L // 2 if z is None else max(0, min(int(z), L - 1))
    stride = max(1, int(stride))
    reader = BpReader(job.store)
    try:
        n = reader.num_steps()
        if n == 0:
            raise FileNotFoundError("no durable output step yet")
        names = [
            v for v in reader.available_variables() if v != "step"
        ]
        name = (field or names[0]).upper()
        if name not in names:
            raise KeyError(
                f"field {field!r} not in store (have "
                f"{sorted(v.lower() for v in names)})"
            )
        plane = reader.get(
            name, step=n - 1, start=[0, 0, z], count=[L, L, 1]
        )[:, :, 0]
        step_arr = reader.get("step", step=n - 1)
    finally:
        reader.close()
    data = plane[::stride, ::stride]
    return {
        "job": job.id,
        "field": name.lower(),
        "z": z,
        "stride": stride,
        "sim_step": int(step_arr),
        "shape": list(data.shape),
        "data": [[round(float(v), 6) for v in row] for row in data],
    }


class _Server(ThreadingHTTPServer):
    """One thread per connection; the listen backlog must absorb a
    whole synthetic-client burst (the load harness opens hundreds of
    sockets in one instant — the stdlib default of 5 resets them)."""

    daemon_threads = True
    request_queue_size = 512


class ServeService:
    """The assembled service: scheduler + worker fleet + HTTP server."""

    def __init__(self, cfg: ServeConfig, *, log: Optional[Logger] = None):
        self.cfg = cfg
        self.log = log or Logger(verbose=True)
        os.makedirs(cfg.state_dir, exist_ok=True)
        if cfg.fleet_dir:
            # Fleet replica: per-member .rank<N> event file + the
            # shared-KV scheduler — any replica can answer for any job.
            from .cluster import ClusterScheduler, arm_fleet_events

            self.events = arm_fleet_events(cfg)
            self.scheduler = ClusterScheduler(
                cfg, role="frontdoor", events=self.events, log=self.log,
            )
        else:
            self.events = _ensure_events(cfg.state_dir)
            self.scheduler = Scheduler(cfg, events=self.events)
        self.scheduler.attach_events()
        self.fleet = WorkerFleet(self.scheduler, cfg, log=self.log)
        from .elastic import ElasticController

        # Off unless GS_SERVE_ELASTIC=1 (start() is then a no-op): the
        # control loop turning queue depth + worker utilization into
        # live mesh reshapes on running batches (docs/SERVICE.md).
        self.elastic = ElasticController(
            self.scheduler, self.fleet if cfg.workers else None,
            log=self.log,
        )
        handler = _make_handler(self)
        self.httpd = _Server((cfg.host, cfg.port), handler)
        if cfg.fleet_dir:
            self.scheduler.announce_endpoint(cfg.host, self.port)
        self._http_thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        """The BOUND port (``GS_SERVE_PORT=0`` = ephemeral, tests)."""
        return self.httpd.server_address[1]

    def start(self) -> "ServeService":
        self.fleet.start()
        self.elastic.start()
        self._http_thread = threading.Thread(
            target=self.httpd.serve_forever, name="gs-serve-http",
            daemon=True,
        )
        self._http_thread.start()
        self.log.info(
            f"gs-serve: listening on {self.cfg.host}:{self.port} "
            f"({self.cfg.workers} worker(s), pack_max="
            f"{self.cfg.pack_max}, events={self.events.describe()})"
        )
        return self

    def close(self, timeout: float = 30.0) -> None:
        """Drain: stop admitting, let workers finish in-flight batches,
        then stop the HTTP loop."""
        self.scheduler.drain()
        self.elastic.close()
        self.fleet.stop(timeout)
        self.scheduler.close()
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._http_thread is not None:
            self._http_thread.join(5.0)

    def __enter__(self) -> "ServeService":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()


def _make_handler(service: ServeService):
    scheduler = service.scheduler

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        server_version = "gs-serve/1"

        # Quiet the default stderr-per-request logging.
        def log_message(self, fmt, *args):  # noqa: ARG002
            pass

        # ------------------------------------------------------- helpers

        def _json(self, code: int, payload: dict) -> None:
            body = (json.dumps(payload) + "\n").encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _error(self, code: int, message: str, **extra) -> None:
            self._json(code, {"error": message, **extra})

        def _job(self, job_id: str):
            return scheduler.jobs.get(job_id)

        # --------------------------------------------------------- POST

        def do_POST(self) -> None:  # noqa: N802 — http.server API
            path = urlparse(self.path).path
            parts = [p for p in path.split("/") if p]
            if parts == ["v1", "jobs"]:
                return self._submit()
            if (len(parts) == 4 and parts[:2] == ["v1", "jobs"]
                    and parts[3] == "cancel"):
                return self._cancel(parts[2])
            self._error(404, f"no such endpoint: POST {path}")

        def _submit(self) -> None:
            try:
                length = int(self.headers.get("Content-Length", 0))
                payload = json.loads(
                    self.rfile.read(length).decode() or "null"
                )
            except (ValueError, UnicodeDecodeError) as e:
                return self._error(400, f"invalid JSON body: {e}")
            try:
                job = scheduler.submit(payload)
            except AdmissionError as e:
                # Valid spec, refused admission: the client's cue to
                # back off (quota) or retry later (queue_full).
                return self._error(
                    429, f"admission refused: {e.reason}",
                    job=e.job.id, reason=e.reason,
                )
            except SettingsError as e:
                # The loud spec-validation contract: the framework's
                # own error text goes straight back to the client.
                return self._error(400, str(e))
            self._json(200, job.describe())

        def _cancel(self, job_id: str) -> None:
            job = self._job(job_id)
            if job is None:
                return self._error(404, f"no such job: {job_id}")
            if scheduler.cancel(job_id):
                return self._json(200, job.describe())
            self._error(
                409,
                f"job {job_id} is {job.state} — only queued jobs "
                "cancel",
            )

        # ---------------------------------------------------------- GET

        def do_GET(self) -> None:  # noqa: N802 — http.server API
            url = urlparse(self.path)
            parts = [p for p in url.path.split("/") if p]
            if parts == ["v1", "healthz"]:
                return self._json(200, {
                    "ok": True, **scheduler.describe(),
                    "launches": service.fleet.launches,
                    "warm_hits": service.fleet.warm_hits,
                })
            if len(parts) >= 3 and parts[:2] == ["v1", "jobs"]:
                job = self._job(parts[2])
                if job is None:
                    return self._error(404, f"no such job: {parts[2]}")
                if len(parts) == 3:
                    return self._json(200, job.describe())
                tail = parts[3]
                if tail == "result":
                    return self._result(job)
                if tail == "field":
                    return self._field(job, parse_qs(url.query))
                if tail == "events":
                    return self._sse(job)
            self._error(404, f"no such endpoint: GET {url.path}")

        def _result(self, job) -> None:
            if job.state not in ("complete", "failed", "cancelled",
                                 "rejected"):
                return self._error(
                    409, f"job {job.id} is {job.state}; result is "
                    "available once terminal",
                )
            self._json(200, job.describe())

        def _field(self, job, qs) -> None:
            try:
                payload = _field_slice(
                    job,
                    field=(qs.get("field") or [None])[0],
                    z=(
                        int(qs["z"][0]) if "z" in qs else None
                    ),
                    stride=int((qs.get("stride") or ["1"])[0]),
                )
            except (FileNotFoundError, KeyError, ValueError,
                    OSError) as e:
                return self._error(404, f"no field slice: {e}")
            self._json(200, payload)

        # ---------------------------------------------------------- SSE

        def _sse(self, job) -> None:
            """Live progress: replay the job's current state, then
            stream its lifecycle + batch run events until terminal.
            Frames are ``event: <kind>`` + JSON data lines; output
            boundaries additionally carry a coarse field slice.

            The per-subscriber queue is BOUNDED (GS_SERVE_SSE_QUEUE):
            a slow client drops frames, it never grows an unbounded
            buffer inside the serving process or blocks the emitting
            run. The idle poll doubles as the disconnect detector —
            the keepalive write to a dead socket raises, the handler
            returns, and ``finally`` unsubscribes the fan-out — and,
            in fleet mode, as the terminal detector: another process's
            ``job_complete`` never flows through THIS process's stream,
            so the refreshed job document is what ends the session."""
            q: "queue.Queue" = queue.Queue(
                maxsize=service.cfg.sse_queue
            )
            ref = {"job": job}

            def fan_out(record: dict) -> None:
                # This job's own lifecycle records, plus its batch's
                # run events (the job snapshot is refreshed on idle —
                # the job may still be queued when the client
                # connects).
                j = ref["job"]
                attrs = record.get("attrs") or {}
                if attrs.get("job") == j.id or (
                    j.batch_id is not None
                    and attrs.get("batch") == j.batch_id
                ):
                    try:
                        q.put_nowait(record)
                    except queue.Full:
                        pass  # slow client: drop, never block the run

            unsubscribe = service.events.subscribe(fan_out)
            try:
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-cache")
                self.end_headers()
                self._sse_frame("state", job.describe())
                terminal = ("complete", "failed", "cancelled",
                            "rejected")
                if job.state in terminal:
                    self._sse_frame("done", job.describe())
                    return
                while True:
                    try:
                        record = q.get(timeout=5.0)
                    except queue.Empty:
                        latest = scheduler.jobs.get(job.id)
                        if latest is not None:
                            ref["job"] = latest
                            if latest.state in terminal:
                                self._sse_frame(
                                    "done", latest.describe()
                                )
                                return
                        self.wfile.write(b": keepalive\n\n")
                        self.wfile.flush()
                        continue
                    kind = record.get("kind")
                    self._sse_frame(kind, record)
                    if kind == "output":
                        try:
                            self._sse_frame(
                                "field_slice",
                                _field_slice(job, stride=max(
                                    1, job.spec.L // 16
                                )),
                            )
                        except (FileNotFoundError, KeyError,
                                ValueError, OSError):
                            pass  # not durable yet: next boundary
                    if kind == "job_complete" and (
                        record.get("attrs", {}).get("job") == job.id
                    ):
                        self._sse_frame("done", job.describe())
                        return
            except OSError:
                pass  # client went away — normal SSE teardown
            finally:
                unsubscribe()

        def _sse_frame(self, event: str, payload: dict) -> None:
            self.wfile.write(
                f"event: {event}\ndata: {json.dumps(payload)}\n\n"
                .encode()
            )
            self.wfile.flush()

    return Handler


def main(argv=None) -> int:
    """CLI entry (``scripts/gs_serve.py``): resolve the GS_SERVE_*
    knobs, start the service, serve until SIGINT/SIGTERM, drain.

    ``--role frontdoor`` (default) runs the HTTP front door —
    standalone, or as a fleet replica when ``GS_SERVE_FLEET_DIR`` is
    set. ``--role worker`` runs a headless fleet worker process
    (``serve/cluster.worker_main``)."""
    import signal

    from .scheduler import resolve_serve_config

    argv = list(argv or [])
    role = "frontdoor"
    if "--role" in argv:
        i = argv.index("--role")
        role = argv[i + 1] if i + 1 < len(argv) else ""
    if role == "worker":
        from .cluster import worker_main

        return worker_main(argv)
    if role != "frontdoor":
        raise SystemExit(
            f"gs-serve: unknown --role {role!r} (frontdoor|worker)"
        )
    cfg = resolve_serve_config()
    service = ServeService(cfg)
    stop = threading.Event()

    def _request_stop(signum, frame):  # noqa: ARG001
        stop.set()

    signal.signal(signal.SIGTERM, _request_stop)
    signal.signal(signal.SIGINT, _request_stop)
    service.start()
    try:
        while not stop.is_set():
            stop.wait(0.5)
    finally:
        service.log.info("gs-serve: draining...")
        service.close()
        service.log.info("gs-serve: bye")
    return 0
