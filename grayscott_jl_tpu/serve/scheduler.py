"""Admission control, per-tenant quotas, priorities, and request packing.

The scheduler is the queue between the HTTP front door
(``serve/server.py``) and the worker fleet (``serve/worker.py``). Its
one structural idea (ROADMAP item 4): a request is just a MEMBER of a
batched ensemble, so "batching" is not a new execution path — the
scheduler groups compatible requests (same :func:`~.protocol.pack_key`)
into one ``[ensemble]``-shaped launch, pads the batch up to a
canonical power-of-two slot count so the worker's warm engine cache
stays warm (idle slots are masked: no stores, no health/stats
pollution — ``ensemble/spec.MemberSpec.active``), and the ensemble
engine does the rest.

Admission control happens at submit time, loudly:

* spec validation (``protocol.parse_job``) raises ``SettingsError``
  -> HTTP 400 with the message;
* a full queue (``GS_SERVE_QUEUE_DEPTH``) or an exhausted per-tenant
  quota (``GS_SERVE_TENANT_QUOTA``) records a REJECTED job (so the
  client can still query why) and emits ``job_rejected`` -> HTTP 429.

Every lifecycle edge lands on the unified GS_EVENTS stream
(``job_submitted`` / ``job_packed`` / ``job_requeued`` /
``job_complete`` / ``job_rejected``; schema in
``scripts/gs_report.py``) and in the shared metrics registry — the
service invents no second telemetry path (docs/SERVICE.md).

Stdlib-only and JAX-free to import; thread-safe (the HTTP handler
threads, the worker threads, and the event subscriber all call in).
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..config.env import env_flag, env_float, env_int, env_str
from ..models.base import SettingsError
from . import cache as cache_mod
from . import protocol

__all__ = [
    "Batch",
    "Job",
    "JOB_STATES",
    "Scheduler",
    "ServeConfig",
    "resolve_serve_config",
]

#: Lifecycle states a job can be in (``Job.state``).
JOB_STATES = (
    "queued", "packed", "running", "complete", "failed", "cancelled",
    "rejected",
)


@dataclasses.dataclass
class ServeConfig:
    """Resolved service configuration (the ``GS_SERVE_*`` knob family,
    docs/SERVICE.md)."""

    host: str = "127.0.0.1"
    port: int = 8642
    workers: int = 1
    queue_depth: int = 256
    tenant_quota: int = 32
    pack_max: int = 8
    pack_window_s: float = 0.05
    slo_s: float = 60.0
    max_l: int = 256
    max_steps: int = 100_000
    state_dir: str = "serve-state"
    supervise: bool = True
    max_requeues: int = 2
    chaos: str = ""
    sse_queue: int = 256
    cache: bool = True
    cache_dir: str = ""
    cache_verify: bool = True
    fleet_dir: str = ""
    replica: str = ""
    fleet_rank: int = 0
    lease_ttl_s: float = 10.0
    heartbeat_s: float = 2.0

    def describe(self) -> dict:
        return dataclasses.asdict(self)


def resolve_serve_config(settings=None) -> ServeConfig:
    """The ``GS_SERVE_*`` env knobs -> :class:`ServeConfig`.

    Env-only (there is no ``[serve]`` TOML table yet: the service is
    launched by ``scripts/gs_serve.py``, not by a simulation config);
    defaults match the dataclass. ``GS_SERVE_CHAOS`` arms a
    consume-once fault-plan string (``resilience/faults.py`` syntax)
    applied to the FIRST batch launched — the worker-kill chaos hook
    ``scripts/chaos_smoke.sh`` scenario 6 drives.
    """
    cfg = ServeConfig(
        host=env_str("GS_SERVE_HOST", "127.0.0.1"),
        port=env_int("GS_SERVE_PORT", 8642),
        workers=env_int("GS_SERVE_WORKERS", 1),
        queue_depth=env_int("GS_SERVE_QUEUE_DEPTH", 256),
        tenant_quota=env_int("GS_SERVE_TENANT_QUOTA", 32),
        pack_max=env_int("GS_SERVE_PACK_MAX", 8),
        pack_window_s=env_float("GS_SERVE_PACK_WINDOW_S", 0.05),
        slo_s=env_float("GS_SERVE_SLO_S", 60.0),
        max_l=env_int("GS_SERVE_MAX_L", 256),
        max_steps=env_int("GS_SERVE_MAX_STEPS", 100_000),
        state_dir=env_str("GS_SERVE_STATE_DIR", "serve-state"),
        supervise=env_flag("GS_SERVE_SUPERVISE", True),
        max_requeues=env_int("GS_SERVE_MAX_REQUEUES", 2),
        chaos=env_str("GS_SERVE_CHAOS", ""),
        sse_queue=env_int("GS_SERVE_SSE_QUEUE", 256),
        cache=cache_mod.resolve_cache_enabled(),
        cache_dir=cache_mod.resolve_cache_dir(),
        cache_verify=cache_mod.resolve_cache_verify(),
        fleet_dir=env_str("GS_SERVE_FLEET_DIR", ""),
        replica=env_str("GS_SERVE_REPLICA", ""),
        fleet_rank=env_int("GS_SERVE_FLEET_RANK", 0),
        lease_ttl_s=env_float("GS_SERVE_LEASE_TTL_S", 10.0),
        heartbeat_s=env_float("GS_SERVE_HEARTBEAT_S", 2.0),
    )
    if cfg.fleet_dir:
        # A fleet member may be a pure front door (workers=0): the
        # compute capacity lives in the shared fleet, not the process.
        if cfg.workers < 0:
            raise ValueError(
                f"GS_SERVE_WORKERS must be >= 0 in fleet mode, got "
                f"{cfg.workers}"
            )
    elif cfg.workers < 1:
        raise ValueError(f"GS_SERVE_WORKERS must be >= 1, got {cfg.workers}")
    if cfg.pack_max < 1:
        raise ValueError(f"GS_SERVE_PACK_MAX must be >= 1, got {cfg.pack_max}")
    if cfg.queue_depth < 1:
        raise ValueError(
            f"GS_SERVE_QUEUE_DEPTH must be >= 1, got {cfg.queue_depth}"
        )
    if cfg.tenant_quota < 1:
        raise ValueError(
            f"GS_SERVE_TENANT_QUOTA must be >= 1, got {cfg.tenant_quota}"
        )
    if cfg.pack_window_s < 0:
        raise ValueError(
            f"GS_SERVE_PACK_WINDOW_S must be >= 0, got {cfg.pack_window_s}"
        )
    if cfg.sse_queue < 1:
        raise ValueError(
            f"GS_SERVE_SSE_QUEUE must be >= 1, got {cfg.sse_queue}"
        )
    if cfg.fleet_rank < 0:
        raise ValueError(
            f"GS_SERVE_FLEET_RANK must be >= 0, got {cfg.fleet_rank}"
        )
    if cfg.lease_ttl_s <= 0:
        raise ValueError(
            f"GS_SERVE_LEASE_TTL_S must be > 0, got {cfg.lease_ttl_s}"
        )
    if not 0 < cfg.heartbeat_s < cfg.lease_ttl_s:
        raise ValueError(
            f"GS_SERVE_HEARTBEAT_S must be in (0, lease_ttl_s="
            f"{cfg.lease_ttl_s}), got {cfg.heartbeat_s} — a lease must "
            "outlive at least one missed heartbeat"
        )
    return cfg


class AdmissionError(Exception):
    """A structurally valid job the service refuses to queue (full
    queue, exhausted tenant quota, drain). Carries the rejected
    :class:`Job` record so the HTTP layer can return its id."""

    def __init__(self, job: "Job", reason: str):
        super().__init__(reason)
        self.job = job
        self.reason = reason


@dataclasses.dataclass
class Job:
    """One request's full lifecycle record."""

    id: str
    tenant: str
    spec: protocol.JobSpec
    state: str = "queued"
    seq: int = 0
    batch_id: Optional[str] = None
    slot: Optional[int] = None
    attempts: int = 0
    error: Optional[str] = None
    submitted_t: float = 0.0
    packed_t: Optional[float] = None
    started_t: Optional[float] = None
    first_step_t: Optional[float] = None
    finished_t: Optional[float] = None
    store: Optional[str] = None
    checkpoint_store: Optional[str] = None
    digest: Optional[str] = None
    cache: Optional[str] = None

    def describe(self) -> dict:
        out = {
            "job": self.id,
            "tenant": self.tenant,
            "state": self.state,
            "priority": self.spec.priority,
            "model": self.spec.model,
            "L": self.spec.L,
            "steps": self.spec.steps,
            "batch": self.batch_id,
            "slot": self.slot,
            "attempts": self.attempts,
            "error": self.error,
            "submitted_t": self.submitted_t,
            "packed_t": self.packed_t,
            "started_t": self.started_t,
            "first_step_t": self.first_step_t,
            "finished_t": self.finished_t,
            "store": self.store,
            "digest": self.digest,
            "cache": self.cache,
        }
        if self.first_step_t is not None:
            out["request_to_first_step_s"] = round(
                self.first_step_t - self.submitted_t, 6
            )
        return out


@dataclasses.dataclass
class Batch:
    """One packed launch: the jobs riding it (slot order) plus the
    launch Settings the worker hands to the driver."""

    id: str
    jobs: List[Job]
    key: Tuple
    n_slots: int
    settings: object  # config.settings.Settings
    dir: str
    supervise: bool = True
    attempt: int = 0
    warm: bool = False
    created_t: float = 0.0
    #: Pending live-reshape request for the worker running this batch
    #: (``{"scale": "grow"|"shrink"}`` or ``{"mesh_dims": [x, y, z]}``;
    #: docs/RESHARD.md "In-job reshapes"). Consume-once via
    #: :meth:`Scheduler.take_reshape`.
    reshape_request: Optional[dict] = None

    @property
    def job_ids(self) -> List[str]:
        return [j.id for j in self.jobs]


def _pow2_slots(n: int, cap: int) -> int:
    """Canonical slot count: the smallest power of two >= n, capped at
    the pack limit — so a 3-job batch runs the same executable shape
    as a 4-job one and the worker's warm cache keeps hitting."""
    slots = 1
    while slots < n:
        slots *= 2
    return min(slots, max(cap, n))


class Scheduler:
    """The multi-tenant queue + packer (docs/SERVICE.md)."""

    def __init__(self, cfg: ServeConfig, *, events=None, metrics=None):
        self.cfg = cfg
        if events is None:
            from ..obs import events as obs_events

            events = obs_events.get_events()
        if metrics is None:
            from ..obs import metrics as obs_metrics

            metrics = obs_metrics.get_metrics()
        self.events = events
        self.metrics = metrics
        self.jobs: Dict[str, Job] = {}
        self.batches: Dict[str, Batch] = {}
        self._queue: List[Job] = []  # pending, FIFO within priority
        self._resume: List[Batch] = []  # requeued batches, FIFO
        self._cond = threading.Condition()
        # Launch nonce: job/batch ids must stay unique across service
        # restarts appending to ONE events file, or the per-tenant
        # report would merge two lives of "j000001" into nonsense.
        self._nonce = os.urandom(3).hex()
        self._seq = 0
        self._batch_seq = 0
        self._closed = False
        #: Set by mark_degraded (SDC quarantine, ``resilience/sdc.py``):
        #: this process's compute inventory is suspect. The solo
        #: scheduler only records it (no peers to route to); the
        #: cluster scheduler stops claiming fresh work.
        self.degraded: Optional[str] = None
        self._chaos_pending = cfg.chaos.strip()
        self._unsubscribe = None
        self.cache: Optional[cache_mod.ResultCache] = None
        if cfg.cache:
            root = cfg.cache_dir or os.path.join(
                cfg.fleet_dir or cfg.state_dir, "cache"
            )
            self.cache = cache_mod.ResultCache(
                root, events=self.events, metrics=self.metrics,
                verify=cfg.cache_verify,
            )

    # ------------------------------------------------------------ events

    def attach_events(self):
        """Subscribe to the unified event stream to track run progress
        (``run_start`` -> running, first ``output``/``checkpoint`` ->
        first-step timestamp) for batches carrying our batch-id bound
        attr. Returns self for chaining; idempotent."""
        if self._unsubscribe is None and self.events.enabled:
            self._unsubscribe = self.events.subscribe(self._on_event)
        return self

    def detach_events(self):
        if self._unsubscribe is not None:
            self._unsubscribe()
            self._unsubscribe = None

    def _on_event(self, record: dict) -> None:
        kind = record.get("kind")
        if kind not in ("run_start", "output", "checkpoint",
                        "run_complete"):
            return
        batch_id = (record.get("attrs") or {}).get("batch")
        if not batch_id:
            return
        with self._cond:
            batch = self.batches.get(batch_id)
            if batch is None:
                return
            ts = record.get("ts") or time.time()
            for job in batch.jobs:
                if kind == "run_start" and job.state == "packed":
                    job.state = "running"
                    job.started_t = job.started_t or ts
                elif kind in ("output", "checkpoint", "run_complete"):
                    # The first evidence of completed compute: the SLO
                    # clock's stop mark (docs/SERVICE.md, "SLO
                    # definitions").
                    if job.first_step_t is None and job.state in (
                        "packed", "running",
                    ):
                        job.first_step_t = ts

    # ------------------------------------------------------------ submit

    def submit(self, payload) -> Job:
        """Admit one client payload. Raises
        :class:`~..models.base.SettingsError` on an invalid spec (HTTP
        400) and :class:`AdmissionError` on a valid-but-refused one
        (HTTP 429); otherwise returns the QUEUED job."""
        spec = protocol.parse_job(
            payload, max_l=self.cfg.max_l, max_steps=self.cfg.max_steps
        )
        # Cache probe OUTSIDE the lock: the CRC audit of a cached
        # artifact is I/O, and admission must not serialize behind it.
        digest = cached = None
        if self.cache is not None:
            digest = cache_mod.job_digest(spec)
            cached = self.cache.lookup(digest)
        with self._cond:
            self._seq += 1
            job = Job(
                id=f"j{self._nonce}-{self._seq:05d}",
                tenant=spec.tenant,
                spec=spec,
                seq=self._seq,
                submitted_t=time.time(),
                digest=digest,
            )
            if cached is not None and not self._closed:
                # The determinism dividend (ROADMAP item 4): this exact
                # physics already ran somewhere in the fleet and its
                # CRC-verified store is on disk — answer in
                # O(store-read), consuming no queue slot, no tenant
                # quota, and no worker launch.
                now = time.time()
                job.cache = "hit"
                job.state = "complete"
                job.store = cached["store"]
                job.first_step_t = job.finished_t = now
                self.jobs[job.id] = job
                self.metrics.counter("serve_cache_hits").inc()
                self.events.emit(
                    "job_submitted", job=job.id, tenant=job.tenant,
                    priority=spec.priority, model=spec.model, L=spec.L,
                    steps=spec.steps, cache="hit",
                )
                self.events.emit(
                    "cache_hit", digest=digest, job=job.id,
                    tenant=job.tenant,
                )
                self.events.emit(
                    "job_complete", job=job.id, tenant=job.tenant,
                    status="complete", cache="hit",
                    wall_s=round(now - job.submitted_t, 3),
                )
                self._cond.notify_all()
                return job
            reason = self._admission_reason(job)
            if reason is not None:
                job.state = "rejected"
                job.error = reason
                job.finished_t = time.time()
                self.jobs[job.id] = job
                self.metrics.counter(
                    "serve_jobs_rejected", reason=reason
                ).inc()
                self.events.emit(
                    "job_rejected", job=job.id, tenant=job.tenant,
                    reason=reason,
                )
                raise AdmissionError(job, reason)
            self.jobs[job.id] = job
            self._queue.append(job)
            self._queue.sort(key=lambda j: (-j.spec.priority, j.seq))
            self.metrics.counter("serve_jobs_submitted").inc()
            self.metrics.gauge("serve_queue_depth").set(
                len(self._queue)
            )
            self.events.emit(
                "job_submitted", job=job.id, tenant=job.tenant,
                priority=spec.priority, model=spec.model, L=spec.L,
                steps=spec.steps,
            )
            if self.cache is not None:
                job.cache = "miss"
                self.metrics.counter("serve_cache_misses").inc()
                self.events.emit(
                    "cache_miss", digest=digest, job=job.id,
                    tenant=job.tenant,
                )
            self._cond.notify_all()
            return job

    def _admission_reason(self, job: Job) -> Optional[str]:
        if self._closed:
            return "shutting_down"
        if len(self._queue) >= self.cfg.queue_depth:
            return "queue_full"
        live = sum(
            1 for j in self.jobs.values()
            if j.tenant == job.tenant
            and j.state in ("queued", "packed", "running")
        )
        if live >= self.cfg.tenant_quota:
            return "tenant_quota"
        return None

    # ------------------------------------------------------------ cancel

    def cancel(self, job_id: str) -> bool:
        """Cancel a QUEUED job (packed/running jobs are committed to a
        launch and refuse — HTTP 409). True on success."""
        with self._cond:
            job = self.jobs.get(job_id)
            if job is None or job.state != "queued":
                return False
            self._queue.remove(job)
            job.state = "cancelled"
            job.finished_t = time.time()
            self.metrics.gauge("serve_queue_depth").set(
                len(self._queue)
            )
            self.events.emit(
                "job_complete", job=job.id, tenant=job.tenant,
                status="cancelled",
            )
            return True

    # ------------------------------------------------------------- pack

    def next_batch(self, timeout: float = 0.5) -> Optional[Batch]:
        """The worker-facing pop: a requeued batch if one is waiting,
        else a freshly packed one. Blocks up to ``timeout`` for work,
        then up to ``GS_SERVE_PACK_WINDOW_S`` more for compatible
        requests to fill the batch — the latency/packing trade the SLO
        budget pays for throughput."""
        deadline = time.monotonic() + max(timeout, 0.0)
        with self._cond:
            while True:
                if self._resume:
                    return self._resume.pop(0)
                if self._queue:
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0 or self._closed:
                    return None
                self._cond.wait(remaining)
            head = self._queue[0]
            key = protocol.pack_key(head.spec)
            window_end = time.monotonic() + self.cfg.pack_window_s
            while True:
                compatible = [
                    j for j in self._queue
                    if protocol.pack_key(j.spec) == key
                ]
                if len(compatible) >= self.cfg.pack_max:
                    break
                remaining = window_end - time.monotonic()
                if remaining <= 0 or self._closed:
                    break
                self._cond.wait(remaining)
                if head.state != "queued":
                    # cancelled out from under us — restart the pop
                    return self.next_batch(timeout=0.0)
            jobs = compatible[: self.cfg.pack_max]
            for j in jobs:
                self._queue.remove(j)
            self.metrics.gauge("serve_queue_depth").set(
                len(self._queue)
            )
            return self._build_batch(jobs, key)

    def _build_batch(self, jobs: List[Job], key: Tuple) -> Batch:
        self._batch_seq += 1
        batch_id = f"b{self._nonce}-{self._batch_seq:04d}"
        n_slots = _pow2_slots(len(jobs), self.cfg.pack_max)
        bdir = os.path.join(self.cfg.state_dir, "batches", batch_id)
        os.makedirs(bdir, exist_ok=True)
        settings = protocol.batch_settings(
            [j.spec for j in jobs],
            n_slots=n_slots,
            output=os.path.join(bdir, "gs.bp"),
            checkpoint_output=os.path.join(bdir, "ckpt.bp"),
            names=[j.id for j in jobs],
            supervise=self.cfg.supervise,
        )
        supervise = self.cfg.supervise
        if self._chaos_pending:
            # Consume-once worker-kill chaos (GS_SERVE_CHAOS,
            # chaos_smoke scenario 6): the injected fault models the
            # worker process dying, so the launch runs UNsupervised —
            # recovery must come from the scheduler requeue, not from
            # an in-place supervisor restart.
            settings.faults = self._chaos_pending
            settings.supervise = False
            supervise = False
            self._chaos_pending = ""
        batch = Batch(
            id=batch_id, jobs=jobs, key=key, n_slots=n_slots,
            settings=settings, dir=bdir, supervise=supervise,
            created_t=time.time(),
        )
        self.batches[batch_id] = batch
        from ..ensemble.io import member_path

        now = time.time()
        for slot, job in enumerate(jobs):
            job.state = "packed"
            job.batch_id = batch_id
            job.slot = slot
            job.packed_t = now
            job.attempts += 1
            job.store = member_path(settings.output, slot, n_slots)
            if settings.checkpoint:
                job.checkpoint_store = member_path(
                    settings.checkpoint_output, slot, n_slots
                )
            self.events.emit(
                "job_packed", job=job.id, tenant=job.tenant,
                batch=batch_id, slot=slot, members=len(jobs),
                slots=n_slots,
            )
        self.metrics.histogram("serve_pack_members").observe(
            float(len(jobs))
        )
        return batch

    # ---------------------------------------------------------- requeue

    def requeue(self, batch: Batch, fault: str) -> None:
        """A worker died under this batch (or its launch failed with a
        classified-recoverable fault): hand the WHOLE batch back to the
        queue as a resume unit. The relaunching worker resumes every
        member from the member-store checkpoint quorum
        (``ensemble/io.restore_ensemble``) — or from scratch when no
        checkpoint exists yet; either way the member stores finish
        byte-identical to an uninterrupted run (docs/SERVICE.md)."""
        with self._cond:
            batch.attempt += 1
            # The chaos fault plan is consume-once at SERVICE level
            # (it modelled the worker that just died); a relaunch with
            # the plan still armed would re-kill itself forever.
            if getattr(batch.settings, "faults", ""):
                batch.settings.faults = ""
            for job in batch.jobs:
                job.state = "packed"
                job.attempts += 1
                self.events.emit(
                    "job_requeued", job=job.id, tenant=job.tenant,
                    batch=batch.id, fault=fault,
                    attempt=batch.attempt,
                )
            self.metrics.counter(
                "serve_batches_requeued", fault=fault
            ).inc()
            self._resume.append(batch)
            self._cond.notify_all()

    # --------------------------------------------------------- complete

    def complete(self, batch: Batch, *, ok: bool,
                 error: Optional[str] = None,
                 wall_s: Optional[float] = None) -> None:
        """Worker-reported batch outcome -> per-job terminal states +
        ``job_complete`` events."""
        with self._cond:
            now = time.time()
            for job in batch.jobs:
                job.state = "complete" if ok else "failed"
                job.error = None if ok else error
                job.finished_t = now
                if job.first_step_t is None and ok:
                    job.first_step_t = now
                self.events.emit(
                    "job_complete", job=job.id, tenant=job.tenant,
                    batch=batch.id,
                    status=job.state,
                    wall_s=(
                        round(wall_s, 3) if wall_s is not None else None
                    ),
                )
                if ok and job.first_step_t is not None:
                    self.metrics.histogram(
                        "serve_request_to_first_step_ms"
                    ).observe(
                        (job.first_step_t - job.submitted_t) * 1e3
                    )
            self.metrics.counter(
                "serve_batches_complete", ok=str(ok).lower()
            ).inc()
            self._cond.notify_all()
        if ok and self.cache is not None:
            # Publish OUTSIDE the lock: replication + the CRC audit are
            # store I/O, and admission must not stall behind them. A
            # job whose launch wrote no store (plotgap=0, no
            # checkpoints) simply isn't cacheable — publish declines
            # silently.
            for job in batch.jobs:
                if job.store:
                    self.cache.publish(
                        job.spec, job.store, job=job.id,
                        digest=job.digest,
                    )

    # ---------------------------------------------------------- elastic

    def queue_depth(self) -> int:
        """Current admitted-but-unpacked depth, refreshing the
        ``serve_queue_depth`` gauge as a side effect — the elastic
        controller (``serve/elastic.py``) polls this, so the gauge
        stays live even when no submit/cancel/pack mutation happens."""
        with self._cond:
            depth = len(self._queue)
            self.metrics.gauge("serve_queue_depth").set(depth)
            return depth

    def running_batches(self) -> List[Batch]:
        """Batches with at least one RUNNING member — the population
        the elastic policy may reshape (packed-but-unlaunched batches
        have no live state to move)."""
        with self._cond:
            return [
                b for b in self.batches.values()
                if any(j.state == "running" for j in b.jobs)
            ]

    def request_reshape(self, batch_id: str, req: dict) -> bool:
        """Post a live-reshape request against a RUNNING batch; the
        worker's between-rounds poll (:meth:`take_reshape`) consumes
        it. Latest-wins if one is already pending. False when the
        batch is unknown or has no running member."""
        with self._cond:
            batch = self.batches.get(batch_id)
            if batch is None or not any(
                j.state == "running" for j in batch.jobs
            ):
                return False
            batch.reshape_request = dict(req)
            self._cond.notify_all()
            return True

    def take_reshape(self, batch_id: str) -> Optional[dict]:
        """Consume-once pop of a pending reshape request (the worker's
        ``reshape_poll`` closure calls this between step rounds)."""
        with self._cond:
            batch = self.batches.get(batch_id)
            if batch is None or batch.reshape_request is None:
                return None
            req, batch.reshape_request = batch.reshape_request, None
            return req

    # ----------------------------------------------------------- status

    def status(self, job_id: str) -> Optional[dict]:
        with self._cond:
            # Satellite fix (docs/SERVICE.md): the depth gauge used to
            # refresh only on mutation paths (submit/cancel/pack), so a
            # poll-heavy idle service could report a stale depth
            # forever. Status IS the poll path — refresh here too.
            self.metrics.gauge("serve_queue_depth").set(
                len(self._queue)
            )
            job = self.jobs.get(job_id)
            return None if job is None else job.describe()

    def drain(self) -> None:
        """Stop admitting; queued jobs stay queued for workers to
        finish. Submit rejects with ``shutting_down``."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def close(self) -> None:
        """Full teardown: drain + detach the event subscription. The
        fleet scheduler (``serve/cluster.py``) extends this with
        membership retirement; the server calls ``close`` uniformly."""
        self.drain()
        self.detach_events()

    def announce_endpoint(self, host: str, port: int) -> None:
        """Fleet replicas record their bound HTTP endpoint in the
        shared member doc (``ClusterScheduler``) so peers and
        launchers can discover ephemeral ports; the single-process
        scheduler has nobody to tell."""

    def idle(self) -> bool:
        """No queued work and no in-flight batches."""
        with self._cond:
            if self._queue or self._resume:
                return False
            return not any(
                j.state in ("packed", "running")
                for j in self.jobs.values()
            )

    def mark_degraded(self, reason: str = "") -> None:
        """Record that this process's devices are suspect (an SDC
        classification the supervisor could not recover in place,
        ``resilience/sdc.py``). The base scheduler only echoes it —
        with no peers there is nobody else to serve the queue."""
        self.degraded = reason or "degraded"
        self.events.emit("worker_degraded", reason=self.degraded)

    def describe(self) -> dict:
        with self._cond:
            states: Dict[str, int] = {}
            for j in self.jobs.values():
                states[j.state] = states.get(j.state, 0) + 1
            return {
                "queued": len(self._queue),
                "resume_batches": len(self._resume),
                "jobs": states,
                "batches": len(self.batches),
                "degraded": self.degraded,
                "config": self.cfg.describe(),
            }
