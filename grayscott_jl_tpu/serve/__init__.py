"""Simulation-as-a-service: the persistent multi-tenant front door.

Everything below this package turns one CLI launch into one job; this
package turns a long-lived process into a *service* (ROADMAP item 4,
docs/SERVICE.md): clients submit JSON job specs over HTTP, the
scheduler packs compatible requests — keyed by ``(model, L, mesh,
dtype, halo_depth, ...)`` — onto **warm batched ensembles** (the
vmapped member axis from the ensemble engine IS the batcher: a request
is just a member), a supervised worker fleet runs the launches through
the unchanged resilience stack, and progress streams back to clients
off the existing GS_EVENTS stream and metrics registry — no second
telemetry path.

Layering: ``protocol`` and ``scheduler`` are stdlib-only and JAX-free
to import (like ``config/`` and ``obs/``); ``worker`` pulls in the
engine lazily at launch time; ``server`` is the stdlib
``http.server`` front. The scheduler's admission control (queue depth,
per-tenant quotas, size caps) and the worker's requeue path (a killed
worker's in-flight members resume from their member-store quorum step,
``ensemble/io.restore_ensemble`` + ``reshard/plan``) are what make the
process safe to leave running.

Two fleet-scale layers ride on top (ROADMAP item 4): ``cluster`` moves
the scheduler state into a shared filesystem KV namespace so N
front-door replicas and M worker processes act as ONE service (any
replica admits/routes/fails-over any job; a dead worker's lease
expires into a fail-over), and ``cache`` exploits bitwise-deterministic
runs to answer repeated JobSpecs from a content-addressed,
CRC-verified store of finished trajectories — a cache hit is a store
read, not a launch.
"""

from .cache import ResultCache, job_digest  # noqa: F401
from .protocol import JobSpec, pack_key, parse_job  # noqa: F401
from .scheduler import (  # noqa: F401
    Job,
    Scheduler,
    ServeConfig,
    resolve_serve_config,
)
