"""Capture golden Gray-Scott trajectories for the refactor-identity test.

Run from the repo root BEFORE a stencil-core refactor::

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python scripts/make_golden.py

Writes ``tests/golden/grayscott_trajectories.npz`` — exact (u, v) field
bytes after a short run for each covered configuration — and a golden
output store ``tests/golden/gs_golden.bp`` written through the full CLI
driver. ``tests/unit/test_models.py::TestGoldenTrajectory`` replays the
same configurations and asserts byte-identical results, so any refactor
of the Gray-Scott update path that changes a single bit fails loudly.
"""

import os
import pathlib
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault(
    "XLA_FLAGS", "--xla_force_host_platform_device_count=8"
)

ROOT = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(ROOT))

import numpy as np  # noqa: E402

from grayscott_jl_tpu.config.settings import Settings  # noqa: E402
from grayscott_jl_tpu.simulation import Simulation  # noqa: E402

OUT = ROOT / "tests" / "golden"

PARAMS = dict(Du=0.2, Dv=0.1, F=0.02, k=0.048, dt=1.0)

#: (tag, n_devices, kernel_language, extra-env GS_FUSE) — the refactor-
#: sensitive paths: single-device XLA, sharded XLA window chain, and the
#: sharded Pallas xy-chain (XLA fallback body on CPU).
CASES = [
    ("single_xla", 1, "Plain", None),
    ("sharded_xla", 8, "Plain", "2"),
    ("sharded_pallas", 8, "Pallas", "2"),
]


def main() -> None:
    OUT.mkdir(parents=True, exist_ok=True)
    arrays = {}
    for tag, n_devices, lang, fuse in CASES:
        if fuse is not None:
            os.environ["GS_FUSE"] = fuse
        else:
            os.environ.pop("GS_FUSE", None)
        sim = Simulation(
            Settings(
                L=16, noise=0.1, precision="Float32", backend="CPU",
                kernel_language=lang, **PARAMS,
            ),
            n_devices=n_devices, seed=7,
        )
        sim.iterate(10)
        u, v = sim.get_fields()
        arrays[f"{tag}_u"] = np.asarray(u)
        arrays[f"{tag}_v"] = np.asarray(v)
        print(f"{tag}: u[0,0,0]={arrays[f'{tag}_u'][0, 0, 0]!r}")
    os.environ.pop("GS_FUSE", None)
    np.savez(OUT / "grayscott_trajectories.npz", **arrays)

    # Golden CLI store: the full driver path (output stream + checkpoint)
    # at L=16 for 6 steps, plotgap 2 — U/V payload bytes per output step
    # are what the identity test compares.
    import shutil
    import tempfile

    from grayscott_jl_tpu import driver

    store = OUT / "gs_golden.bp"
    if store.exists():
        shutil.rmtree(store)
    with tempfile.TemporaryDirectory() as td:
        cfg = pathlib.Path(td) / "golden.toml"
        cfg.write_text(
            "L = 16\nsteps = 6\nplotgap = 2\nnoise = 0.1\n"
            "Du = 0.2\nDv = 0.1\nF = 0.02\nk = 0.048\ndt = 1.0\n"
            f"output = \"{store}\"\n"
            "precision = \"Float32\"\nbackend = \"CPU\"\n"
            "kernel_language = \"Plain\"\n"
        )
        os.environ["GS_ASYNC_IO_DEPTH"] = "0"
        os.environ["GS_SEED"] = "7"
        try:
            driver.main([str(cfg)], n_devices=1)
        finally:
            os.environ.pop("GS_ASYNC_IO_DEPTH", None)
            os.environ.pop("GS_SEED", None)
    print(f"golden store at {store}")


if __name__ == "__main__":
    main()
