#!/usr/bin/env python3
"""Launch the simulation service (docs/SERVICE.md).

    GS_SERVE_PORT=8642 python scripts/gs_serve.py

Fleet mode (docs/SERVICE.md, "the distributed fleet") — every member
shares GS_SERVE_FLEET_DIR and gets a unique GS_SERVE_FLEET_RANK::

    GS_SERVE_FLEET_DIR=/shared/fleet GS_SERVE_FLEET_RANK=0 \\
        python scripts/gs_serve.py                     # front door
    GS_SERVE_FLEET_DIR=/shared/fleet GS_SERVE_FLEET_RANK=2 \\
        python scripts/gs_serve.py --role worker       # worker

All configuration rides the ``GS_SERVE_*`` env knob family (resolved
by ``grayscott_jl_tpu.serve.scheduler.resolve_serve_config``; table in
docs/SERVICE.md and README). SIGTERM/SIGINT drain the service: no new
admissions, in-flight batches finish, then the process exits.
"""

import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from grayscott_jl_tpu.serve.server import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
