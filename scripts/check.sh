#!/usr/bin/env bash
# Pre-push gate: gslint + ruff + mypy (when installed) + quick pytest.
#
#   scripts/check.sh          # full chain
#   scripts/check.sh --fast   # static checks only, no pytest
#
# Mirrors tests/unit/test_static_suite.py — the same steps run in
# tier-1, so a green check.sh is a green static gate in CI.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== gslint =="
python scripts/gslint.py grayscott_jl_tpu scripts bench.py

if python -m ruff --version >/dev/null 2>&1; then
    echo "== ruff =="
    python -m ruff check .
    python -m ruff format --check grayscott_jl_tpu/lint
else
    echo "== ruff: not installed, skipping =="
fi

if python -m mypy --version >/dev/null 2>&1; then
    echo "== mypy --strict (JAX-free modules) =="
    python -m mypy --strict \
        grayscott_jl_tpu/models/base.py \
        grayscott_jl_tpu/obs/events.py \
        grayscott_jl_tpu/reshard/plan.py \
        grayscott_jl_tpu/lint
else
    echo "== mypy: not installed, skipping =="
fi

if [[ "${1:-}" != "--fast" ]]; then
    echo "== quick pytest (unit + integrity chaos, not slow) =="
    # The functional integrity-chaos file rides along (mirrors
    # .github/workflows/check.yml): the fail-silent contracts —
    # bitflip detection, ckpt_corrupt failover, sole-replica refusal —
    # hold on every push (docs/RESILIENCE.md "Data integrity").
    # test_precision_run rides along too: the codec's byte-identity
    # and drift-gate recovery contracts (docs/PRECISION.md).
    # tests/unit includes test_kernelgen.py — the interpret-mode
    # generated-kernel equality contracts (GS bitwise vs the hand
    # kernel's golden, every model vs its XLA trajectory at the
    # documented tolerance; docs/KERNELGEN.md) hold on every push.
    # test_halo_depth.py rides in tests/unit as well: the Pallas
    # s-step program-identity contract (halo_depth=k bitwise vs
    # GS_FUSE=k*d, all models, interpret mode) and the VMEM
    # feasibility gate (docs/TEMPORAL.md) hold on every push.
    # test_sdc_run rides along: the compute-path SDC walk — detect,
    # verified-checkpoint resume, quarantine + reshape, stores
    # content-identical — plus the screening-off fault-blindness
    # control (docs/RESILIENCE.md "Silent data corruption"); the
    # tests/unit leg already carries test_sdc.py's transparency
    # matrix and supervisor-ladder contracts.
    JAX_PLATFORMS=cpu python -m pytest tests/unit \
        tests/functional/test_integrity_run.py \
        tests/functional/test_precision_run.py \
        tests/functional/test_sdc_run.py -q -m 'not slow' \
        -p no:cacheprovider
fi
echo "check.sh: OK"
