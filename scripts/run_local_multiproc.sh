#!/usr/bin/env bash
# Run an N-process distributed simulation on one machine (CPU devices) —
# the development/testing analog of the reference's oversubscribed
# `mpirun -n 4` (test/runtests.jl). Each process gets DEVICES_PER_PROC
# virtual CPU devices; the global mesh spans all of them.
#
# Usage: ./scripts/run_local_multiproc.sh <nprocs> <config.toml> [devices_per_proc]

set -euo pipefail

NPROCS="${1:?nprocs}"
CONFIG="${2:?config.toml}"
DEV="${3:-4}"
PORT="${PORT:-$(( (RANDOM % 20000) + 20000 ))}"
REPO="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"

pids=()
cleanup() {
  # If any rank dies, survivors hang in collectives waiting for it —
  # kill the whole group so the script exits instead of wedging.
  for p in "${pids[@]}"; do
    kill "$p" 2>/dev/null || true
  done
}
trap cleanup EXIT

for ((i = 0; i < NPROCS; i++)); do
  GS_TPU_COORDINATOR="127.0.0.1:${PORT}" \
  GS_TPU_NUM_PROCESSES="${NPROCS}" \
  GS_TPU_PROCESS_ID="${i}" \
  JAX_PLATFORMS=cpu \
  XLA_FLAGS="--xla_force_host_platform_device_count=${DEV}" \
  PYTHONPATH="${REPO}${PYTHONPATH:+:${PYTHONPATH}}" \
  python3 "${REPO}/gray-scott.py" "${CONFIG}" &
  pids+=($!)
done

rc=0
# wait -n returns as each rank finishes; first failure kills the rest.
for ((i = 0; i < NPROCS; i++)); do
  if ! wait -n; then
    rc=1
    cleanup
  fi
done
trap - EXIT
exit "$rc"
