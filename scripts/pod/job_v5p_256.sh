#!/usr/bin/env bash
# v5p-256 job: 128 chips / 32 hosts — the weak-scaling workload
# (BASELINE.json config #5: L=1024, checkpoint + parallel output).
#
#   ./scripts/pod/job_v5p_256.sh [config.toml]
#
# Provisioning (once):
#   gcloud compute tpus tpu-vm create "$TPU_NAME" --zone "$ZONE" \
#     --accelerator-type v5p-256 --version v2-alpha-tpuv5
#   gcloud compute tpus tpu-vm scp --recurse . "$TPU_NAME":~/grayscott \
#     --zone "$ZONE" --worker=all

set -euo pipefail
HERE="$(cd "$(dirname "${BASH_SOURCE[0]}")" && pwd)"
source "${HERE}/config_v5p_256.sh"
CONFIG="${1:-examples/settings-weakscale-v5p256.toml}"
exec "${HERE}/../run_tpu_pod.sh" "${TPU_NAME}" "${ZONE}" "${CONFIG}"
