# Environment for a v5p-256 slice (128 chips, 32 hosts) — the
# weak-scaling target topology (BASELINE.json config #5). TPU analog of
# the reference's largest-site config (config_summit.sh).
#
# Topology facts this config encodes:
#   * v5p-256 = 128 chips across 32 hosts.
#   * 128 chips -> CartDomain.dims_create picks an 8x4x4 mesh; requires
#     L divisible by 8 on x and 4 on y/z — L=1024 gives 128x256x256
#     blocks/chip.
#   * Checkpointing at this scale: per-shard selection restore means a
#     restart never gathers the global array (simulation.py
#     restore_from_reader); keep checkpoint = true in the config.
#
# Usage: source this, then scripts/pod/job_v5p_256.sh.

export TPU_NAME="${TPU_NAME:-gs-v5p-256}"
export ZONE="${ZONE:-us-east5-a}"
export ACCELERATOR_TYPE="v5p-256"

export GS_FUSE="${GS_FUSE:-5}"
export GS_TPU_STATS="${GS_TPU_STATS:-/tmp/gs_stats.json}"
# export GS_TPU_PROFILE=/tmp/gs_trace
