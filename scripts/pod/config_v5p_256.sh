# Environment for a v5p-256 slice (128 chips, 32 hosts) — the
# weak-scaling target topology (BASELINE.json config #5). TPU analog of
# the reference's largest-site config (config_summit.sh).
#
# Topology facts this config encodes:
#   * v5p-256 = 128 chips across 32 hosts.
#   * 128 chips -> CartDomain.dims_create picks an 8x4x4 mesh; requires
#     L divisible by 8 on x and 4 on y/z — L=1024 gives 128x256x256
#     blocks/chip.
#   * Checkpointing at this scale: per-shard selection restore means a
#     restart never gathers the global array (simulation.py
#     restore_from_reader); keep checkpoint = true in the config.
#
# Usage: source this, then scripts/pod/job_v5p_256.sh.

export TPU_NAME="${TPU_NAME:-gs-v5p-256}"
export ZONE="${ZONE:-us-east5-a}"
export ACCELERATOR_TYPE="v5p-256"

# Kernel-language mesh choice at 128 chips / L=1024 (the ici_model.py
# r4 mixed-mesh sweep over all 128-chip factorizations). The example
# TOML ships kernel_language = "Auto": the ICI model resolves the
# language per config at construction (efficiency objective by
# default -> the >=90% holder; GS_AUTO_OBJECTIVE=throughput -> the
# fastest absolute chain). Pin a language in the TOML to override.
#   * XLA kernel: leave GS_TPU_MESH_DIMS unset -> dims_create 8x4x4
#     (projected weak-scaling 0.994 — the >=90% target holder at this
#     exact config; what Auto's default picks).
#   * Pallas kernel: export GS_TPU_MESH_DIMS=16,8,1 + GS_FUSE=4 — the
#     xy-chain (in-kernel fused schedule across x AND y, z unsharded)
#     projects 0.829, up from 0.68 for the retired per-stage design.
#     At 4.2M cells/chip the remaining gap is structural surface work
#     (y-halo sublane tile >= 8 rows + x ring + comm); at L=2048 the
#     sweep's best (8,8,2 with z bands, k=3) recovers to 0.85 and the
#     chain approaches the target regime as locals grow.
# export GS_TPU_MESH_DIMS=16,8,1

export GS_FUSE="${GS_FUSE:-5}"
export GS_TPU_STATS="${GS_TPU_STATS:-/tmp/gs_stats.json}"
# export GS_TPU_PROFILE=/tmp/gs_trace
