# Environment for a v5p-8 slice (4 chips, 1 host) — TPU analog of the
# reference's per-site config scripts (config_summit.sh:1-20).
#
# Topology facts this config encodes:
#   * v5p counts cores: v5p-8 = 4 chips on one host.
#   * 4 chips -> CartDomain.dims_create picks a 2x2x1 mesh; halo
#     ppermutes ride single-hop ICI links on the 3D torus
#     (mesh_utils.create_device_mesh maps logical->physical).
#   * 95 GiB HBM/chip and ~2.8 TB/s: per-chip L-blocks up to ~1500^3 fit;
#     the roofline scales the v5e numbers by ~3.4x (BASELINE.md).
#
# Usage: source this, then scripts/pod/job_v5p_8.sh (or run_tpu_pod.sh).

export TPU_NAME="${TPU_NAME:-gs-v5p-8}"
export ZONE="${ZONE:-us-east5-a}"
export ACCELERATOR_TYPE="v5p-8"

# 1D x-sharded mesh: the Pallas kernel's in-kernel fused chain can
# cross the shard boundary when x faces are the only halos (they ride
# the leading dim), so sharded steps run at the fused single-chip
# schedule — the fastest layout for kernel_language=Pallas at this
# scale (BASELINE.md "ICI weak scaling"). Unset to fall back to the
# MPI-style dims_create 3D factorization (the right choice for the
# XLA language and for >16 chips). Ignored by single-device runs.
export GS_TPU_MESH_DIMS="${GS_TPU_MESH_DIMS:-4,1,1}"

export GS_FUSE="${GS_FUSE:-5}"
export GS_TPU_STATS="${GS_TPU_STATS:-/tmp/gs_stats.json}"
# export GS_TPU_PROFILE=/tmp/gs_trace
