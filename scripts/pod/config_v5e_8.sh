# Environment for a v5e-8 slice (8 chips, 1 host) — the TPU analog of the
# reference's per-site config scripts (config_summit.sh:1-20: module
# loads + MPI binary selection; here: mesh/kernel tuning knobs).
#
# Topology facts this config encodes:
#   * 8 chips -> CartDomain.dims_create picks a 2x2x2 logical mesh; the
#     v5e-8 ICI is a 2D torus, so mesh_utils.create_device_mesh maps the
#     third logical axis onto it (simulation.py warns if it cannot).
#   * 16 GiB HBM/chip: L=256 f32 shards to 128^3 blocks/chip — far below
#     memory limits; L up to ~1024 fits comfortably.
#   * v5e VMEM is 128 MiB/core: the Pallas kernel's automatic slab/fuse
#     selection (GS_FUSE default 5 since the r3 op-diet) is measured fastest at L>=128.
#
# Usage: source this, then scripts/pod/job_v5e_8.sh (or run_tpu_pod.sh).

export TPU_NAME="${TPU_NAME:-gs-v5e-8}"
export ZONE="${ZONE:-us-west4-a}"
export ACCELERATOR_TYPE="v5litepod-8"

# 1D x-sharded mesh: at <=16 chips the Pallas kernel's in-kernel fused
# chain can cross the shard boundary (x halos are its leading-dim
# element), so sharded steps run at the fused single-chip schedule —
# the fastest pod-slice layout for kernel_language=Pallas (projected
# weak-scaling 0.80-0.90 vs 0.67 on the 3D mesh, BASELINE.md). Unset
# to fall back to the MPI-style dims_create 3D factorization (the
# right choice for the XLA language and for >16 chips).
export GS_TPU_MESH_DIMS="${GS_TPU_MESH_DIMS:-8,1,1}"

# Temporal-blocking depth for the single-block Pallas path; sharded runs
# use the k-deep wide-halo exchange with the same depth (simulation.py).
export GS_FUSE="${GS_FUSE:-5}"
# Per-phase wall-clock + cell-updates/s JSON, one file per process.
export GS_TPU_STATS="${GS_TPU_STATS:-/tmp/gs_stats.json}"
# Uncomment for a jax.profiler device trace of the run:
# export GS_TPU_PROFILE=/tmp/gs_trace
