# Environment for a v5e-8 slice (8 chips, 1 host) — the TPU analog of the
# reference's per-site config scripts (config_summit.sh:1-20: module
# loads + MPI binary selection; here: mesh/kernel tuning knobs).
#
# Topology facts this config encodes:
#   * 8 chips -> CartDomain.dims_create picks a 2x2x2 logical mesh; the
#     v5e-8 ICI is a 2D torus, so mesh_utils.create_device_mesh maps the
#     third logical axis onto it (simulation.py warns if it cannot).
#   * 16 GiB HBM/chip: L=256 f32 shards to 128^3 blocks/chip — far below
#     memory limits; L up to ~1024 fits comfortably.
#   * v5e VMEM is 128 MiB/core: the Pallas kernel's automatic slab/fuse
#     selection (GS_FUSE default 5 since the r3 op-diet) is measured fastest at L>=128.
#
# Usage: source this, then scripts/pod/job_v5e_8.sh (or run_tpu_pod.sh).

export TPU_NAME="${TPU_NAME:-gs-v5e-8}"
export ZONE="${ZONE:-us-west4-a}"
export ACCELERATOR_TYPE="v5litepod-8"

# 2D (x,y)-sharded mesh: the round-4 xy-chain runs the in-kernel fused
# schedule across BOTH sharded axes (y rides the cheap sublane tiling;
# z stays unsharded so no 128-lane padding and no band correction) —
# the fastest layout for kernel_language=Pallas at this scale:
# projected weak-scaling 0.82 at L=256 vs 0.80 for the 1D x-chain and
# 0.67 for the retired per-stage 3D design (benchmarks/ici_model.py
# sweep, r4 artifact). Unset to fall back to the MPI-style dims_create
# 3D factorization (the right choice for the XLA language).
export GS_TPU_MESH_DIMS="${GS_TPU_MESH_DIMS:-4,2,1}"

# Temporal-blocking depth. k=4 keeps the xy-chain's y halo exactly one
# sublane tile (2k = 8 rows, zero alignment filler) — the sweep's
# optimum for every xy-sharded config.
export GS_FUSE="${GS_FUSE:-4}"
# Per-phase wall-clock + cell-updates/s JSON, one file per process.
export GS_TPU_STATS="${GS_TPU_STATS:-/tmp/gs_stats.json}"
# Uncomment for a jax.profiler device trace of the run:
# export GS_TPU_PROFILE=/tmp/gs_trace
