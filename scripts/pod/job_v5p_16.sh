#!/usr/bin/env bash
# v5p-16 job: 8 chips / 2 hosts — first multi-host rung; the command
# fans out to both workers and jax.distributed.initialize wires them
# (reference analog: the multi-rank jsrun lines, job_summit.sh:22-26).
#
#   ./scripts/pod/job_v5p_16.sh [config.toml]
#
# Provisioning (once):
#   gcloud compute tpus tpu-vm create "$TPU_NAME" --zone "$ZONE" \
#     --accelerator-type v5p-16 --version v2-alpha-tpuv5
#   gcloud compute tpus tpu-vm scp --recurse . "$TPU_NAME":~/grayscott \
#     --zone "$ZONE" --worker=all

set -euo pipefail
HERE="$(cd "$(dirname "${BASH_SOURCE[0]}")" && pwd)"
source "${HERE}/config_v5p_16.sh"
CONFIG="${1:-examples/settings-pod-v5p16.toml}"
exec "${HERE}/../run_tpu_pod.sh" "${TPU_NAME}" "${ZONE}" "${CONFIG}"
