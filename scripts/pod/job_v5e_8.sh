#!/usr/bin/env bash
# v5e-8 job: 8-chip single-host run of the pod-slice workload — the TPU
# analog of the reference's batch scripts (job_summit.sh:1-27: allocate,
# set env, run one workload).
#
#   ./scripts/pod/job_v5e_8.sh [config.toml]
#
# Provisioning (once):
#   gcloud compute tpus tpu-vm create "$TPU_NAME" --zone "$ZONE" \
#     --accelerator-type v5litepod-8 --version v2-alpha-tpuv5-lite
#   gcloud compute tpus tpu-vm scp --recurse . "$TPU_NAME":~/grayscott \
#     --zone "$ZONE" --worker=all

set -euo pipefail
HERE="$(cd "$(dirname "${BASH_SOURCE[0]}")" && pwd)"
source "${HERE}/config_v5e_8.sh"
CONFIG="${1:-examples/settings-pod-slice.toml}"
exec "${HERE}/../run_tpu_pod.sh" "${TPU_NAME}" "${ZONE}" "${CONFIG}"
