#!/usr/bin/env bash
# v5p-8 job: 4-chip single-host run (reference analog: job_summit.sh).
#
#   ./scripts/pod/job_v5p_8.sh [config.toml]
#
# Provisioning (once):
#   gcloud compute tpus tpu-vm create "$TPU_NAME" --zone "$ZONE" \
#     --accelerator-type v5p-8 --version v2-alpha-tpuv5
#   gcloud compute tpus tpu-vm scp --recurse . "$TPU_NAME":~/grayscott \
#     --zone "$ZONE" --worker=all

set -euo pipefail
HERE="$(cd "$(dirname "${BASH_SOURCE[0]}")" && pwd)"
source "${HERE}/config_v5p_8.sh"
CONFIG="${1:-examples/settings-pod-slice.toml}"
exec "${HERE}/../run_tpu_pod.sh" "${TPU_NAME}" "${ZONE}" "${CONFIG}"
