# Environment for a v5p-16 slice (8 chips, 2 hosts) — TPU analog of the
# reference's per-site config scripts (config_summit.sh:1-20). First
# multi-HOST topology: one process per host, jax.distributed.initialize
# autodetects the slice (GS_TPU_DISTRIBUTED=auto, set by run_tpu_pod.sh).
#
# Topology facts this config encodes:
#   * v5p-16 = 8 chips across 2 hosts (4 chips/host).
#   * 8 chips -> CartDomain.dims_create picks a 2x2x2 mesh, mapped onto
#     the v5p 3D torus so each of the 6 halo faces is a single ICI hop.
#   * Each process owns 4 chip-shards; output is per-process multi-writer
#     (data.<w> blocks merged on read — io/bplite.py), no MPI-IO analog
#     needed.
#
# Usage: source this, then scripts/pod/job_v5p_16.sh.

export TPU_NAME="${TPU_NAME:-gs-v5p-16}"
export ZONE="${ZONE:-us-east5-a}"
export ACCELERATOR_TYPE="v5p-16"

# The example TOML ships kernel_language = "Auto" (resolved per config
# by the ICI model: efficiency objective -> the >=90% holder, which is
# the XLA kernel here; GS_AUTO_OBJECTIVE=throughput -> the Pallas
# xy-chain). The mesh/fuse exports below serve the Pallas choice and
# are harmless for XLA.
#
# 2D (x,y)-sharded mesh: the round-4 xy-chain runs the in-kernel fused
# schedule across BOTH sharded axes — local blocks 128x256x512, the
# mixed-mesh sweep's best for kernel_language=Pallas at this config
# (projected weak-scaling 0.895 vs 0.858 for the 1D x-chain, whose
# 64x512x512 local caps the feasible depth at 3, and 0.68 for the
# retired per-stage 3D design — benchmarks/ici_model.py r4 artifact).
# Unset to fall back to the MPI-style dims_create 3D factorization
# (the right choice for the XLA language). Ignored by single-device
# runs.
export GS_TPU_MESH_DIMS="${GS_TPU_MESH_DIMS:-4,2,1}"

# Chain depth. k=4 keeps the xy-chain's y halo exactly one sublane
# tile (2k = 8 rows, zero alignment filler) and fits VMEM at this
# local shape (the dispatch would cap an infeasible depth with a
# warning either way); the XLA wide-halo chain is depth-insensitive
# between 4 and 5, so one export serves both languages.
export GS_FUSE="${GS_FUSE:-4}"
export GS_TPU_STATS="${GS_TPU_STATS:-/tmp/gs_stats.json}"
# export GS_TPU_PROFILE=/tmp/gs_trace
