# Environment for a v5p-16 slice (8 chips, 2 hosts) — TPU analog of the
# reference's per-site config scripts (config_summit.sh:1-20). First
# multi-HOST topology: one process per host, jax.distributed.initialize
# autodetects the slice (GS_TPU_DISTRIBUTED=auto, set by run_tpu_pod.sh).
#
# Topology facts this config encodes:
#   * v5p-16 = 8 chips across 2 hosts (4 chips/host).
#   * 8 chips -> CartDomain.dims_create picks a 2x2x2 mesh, mapped onto
#     the v5p 3D torus so each of the 6 halo faces is a single ICI hop.
#   * Each process owns 4 chip-shards; output is per-process multi-writer
#     (data.<w> blocks merged on read — io/bplite.py), no MPI-IO analog
#     needed.
#
# Usage: source this, then scripts/pod/job_v5p_16.sh.

export TPU_NAME="${TPU_NAME:-gs-v5p-16}"
export ZONE="${ZONE:-us-east5-a}"
export ACCELERATOR_TYPE="v5p-16"

# 1D x-sharded mesh: the Pallas kernel's in-kernel fused chain can
# cross the shard boundary when x faces are the only halos (they ride
# the leading dim), so sharded steps run at the fused single-chip
# schedule — the fastest layout for kernel_language=Pallas at this
# scale (BASELINE.md "ICI weak scaling"). Unset to fall back to the
# MPI-style dims_create 3D factorization (the right choice for the
# XLA language and for >16 chips). Ignored by single-device runs.
export GS_TPU_MESH_DIMS="${GS_TPU_MESH_DIMS:-8,1,1}"

# Chain depth. NOTE the two kernel languages diverge on this config:
# the XLA wide-halo chain has no VMEM constraint and wants the measured
# optimum k=5, while the Pallas x-chain on the 64x512x512-f32 local
# block only fits Mosaic's VMEM at fuse=3 (bx=4) — the dispatch caps it
# there automatically (simulation.py max_feasible_fuse guard, with a
# warning), trimming the exchange width to match. So 5 is right for
# both: Pallas runs depth 3 either way, XLA keeps its full
# amortization.
export GS_FUSE="${GS_FUSE:-5}"
export GS_TPU_STATS="${GS_TPU_STATS:-/tmp/gs_stats.json}"
# export GS_TPU_PROFILE=/tmp/gs_trace
