"""Regenerate the kernel-generator goldens (docs/KERNELGEN.md).

Two committed artifacts back the generator's correctness contract in
tier-1 (``tests/unit/test_kernelgen.py``):

* ``tests/golden/pallas_hand_kernel.npz`` — exact field bytes of the
  HAND-WRITTEN Gray-Scott Pallas kernel over seven refactor-sensitive
  configs (single-block fuse=1/3, full 12-face mode, x-chain, xy-chain
  operand, GS_MID_BF16 mids, bf16 storage posture). The committed file
  was captured at the last pre-generator commit with the old kernel;
  the generated kernel must replay it BITWISE. Re-running this script
  regenerates it **through the generated kernel** — only do that when
  the kernel program is intentionally changed (and say so in the PR),
  because it re-anchors the identity gate to the current code.
* ``tests/golden/model_trajectories.npz`` — 10-step XLA (``Plain``)
  trajectories for every non-flagship model at L=16, the reference the
  generated kernels must match at the documented tolerance
  (docs/KERNELGEN.md "Equality fine print").

Run from the repo root::

    JAX_PLATFORMS=cpu python scripts/make_kernelgen_golden.py
"""

import os
import pathlib
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

ROOT = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(ROOT))

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from grayscott_jl_tpu.config.settings import Settings  # noqa: E402
from grayscott_jl_tpu.models import get_model, grayscott  # noqa: E402
from grayscott_jl_tpu.ops import kernelgen, pallas_stencil  # noqa: E402
from grayscott_jl_tpu.simulation import Simulation  # noqa: E402

OUT = ROOT / "tests" / "golden"

GS = dict(Du=0.2, Dv=0.1, F=0.02, k=0.048, dt=1.0)


def _params(noise, dtype=jnp.float32):
    s = Settings(L=16, noise=noise, precision="Float32", backend="CPU",
                 kernel_language="Pallas", **GS)
    return grayscott.Params.from_settings(s, dtype)


def _rand(shape, seed):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.random(shape), jnp.float32)


def capture_kernel_configs() -> None:
    """The seven bitwise-gate configs, through the generated kernel."""
    spec = kernelgen.get_spec(grayscott.MODEL)
    step = pallas_stencil.fused_step
    arrays = {}

    # 1. single-block, fuse=1, noise on, seeded GS init (flagship)
    u, v = grayscott.init_fields(16, jnp.float32)
    seeds = jnp.asarray([123, 456, 7], jnp.int32)
    for i in range(4):
        u, v = step((u, v), _params(0.1), seeds.at[2].add(i),
                    spec=spec, use_noise=True)
    arrays["single_f1_u"], arrays["single_f1_v"] = (np.asarray(u),
                                                    np.asarray(v))

    # 2. single-block temporal chain fuse=3, random fields
    u, v = _rand((16, 16, 16), 1), _rand((16, 16, 16), 2)
    u3, v3 = step((u, v), _params(0.25),
                  jnp.asarray([9, 17, 5], jnp.int32),
                  spec=spec, use_noise=True, fuse=3)
    arrays["single_f3_u"], arrays["single_f3_v"] = (np.asarray(u3),
                                                    np.asarray(v3))

    # 3. full-faces mode (6n-tuple: axis-major, field-major lo/hi)
    L = 16
    u, v = _rand((L, L, L), 3), _rand((L, L, L), 4)
    shapes = [(1, L, L)] * 4 + [(L, 1, L)] * 4 + [(L, L, 1)] * 4
    faces = tuple(_rand(s, 10 + i) for i, s in enumerate(shapes))
    uf, vf = step((u, v), _params(0.1),
                  jnp.asarray([3, 1, 9], jnp.int32), faces,
                  spec=spec, use_noise=True)
    arrays["faces12_u"], arrays["faces12_v"] = (np.asarray(uf),
                                                np.asarray(vf))

    # 4. x-chain mode (2n-tuple fuse-wide x faces), k=2, interior shard
    nx, ny, nz, k = 16, 8, 128, 2
    u, v = _rand((nx, ny, nz), 5), _rand((nx, ny, nz), 6)
    xfaces = tuple(_rand((k, ny, nz), 30 + i) for i in range(4))
    ux, vx = step((u, v), _params(0.2),
                  jnp.asarray([3, 5, 11], jnp.int32), xfaces,
                  spec=spec, use_noise=True, fuse=k,
                  offsets=jnp.asarray([16, 0, 0], jnp.int32),
                  row=jnp.int32(64))
    arrays["xchain_u"], arrays["xchain_v"] = (np.asarray(ux),
                                              np.asarray(vx))

    # 5. xy-chain operand (y-extended block, global-y pinning), k=2
    nx, nz, k = 16, 128, 2
    ny = 8 + 2 * k + 4  # + filler to sublane 16
    u, v = _rand((nx, ny, nz), 7), _rand((nx, ny, nz), 8)
    yfaces = tuple(_rand((k, ny, nz), 40 + i) for i in range(4))
    uy, vy = step((u, v), _params(0.2),
                  jnp.asarray([3, 5, 11], jnp.int32), yfaces,
                  spec=spec, use_noise=True, fuse=k,
                  offsets=jnp.asarray([16, 8 - k, 0], jnp.int32),
                  row=jnp.int32(64))
    arrays["xychain_u"], arrays["xychain_v"] = (np.asarray(uy),
                                                np.asarray(vy))

    # 6. bf16 mid-stage buffers (GS_MID_BF16=1), fuse=3
    os.environ["GS_MID_BF16"] = "1"
    try:
        u, v = _rand((16, 16, 16), 1), _rand((16, 16, 16), 2)
        ub, vb = step((u, v), _params(0.1),
                      jnp.asarray([1, 2, 3], jnp.int32),
                      spec=spec, use_noise=True, fuse=3)
    finally:
        os.environ.pop("GS_MID_BF16")
    arrays["midbf16_u"], arrays["midbf16_v"] = (np.asarray(ub),
                                                np.asarray(vb))

    # 7. bf16 storage posture (bf16 fields, f32 accumulation), fuse=2
    ub16 = _rand((16, 16, 16), 1).astype(jnp.bfloat16)
    vb16 = _rand((16, 16, 16), 2).astype(jnp.bfloat16)
    u2, v2 = step((ub16, vb16), _params(0.1, jnp.bfloat16),
                  jnp.asarray([4, 5, 6], jnp.int32),
                  spec=spec, use_noise=True, fuse=2)
    arrays["bf16_f2_u"] = np.asarray(u2.astype(jnp.float32))
    arrays["bf16_f2_v"] = np.asarray(v2.astype(jnp.float32))

    np.savez(OUT / "pallas_hand_kernel.npz", **arrays)
    for name, a in sorted(arrays.items()):
        print(f"{name}: shape={a.shape} sum={float(a.sum()):.6f}")
    print(f"wrote {OUT / 'pallas_hand_kernel.npz'}")


def capture_model_trajectories() -> None:
    """10-step XLA reference trajectories for the non-flagship models
    (Gray-Scott's XLA reference lives in grayscott_trajectories.npz,
    scripts/make_golden.py)."""
    arrays = {}
    for model in ("brusselator", "fhn", "heat"):
        s = Settings(L=16, noise=0.1, dt=0.05, precision="Float32",
                     backend="CPU", kernel_language="Plain")
        s.model = model
        sim = Simulation(s, n_devices=1, seed=7)
        sim.iterate(10)
        for name, f in zip(get_model(model).field_names,
                           sim.get_fields()):
            arrays[f"{model}_{name}"] = np.asarray(f)
            print(f"{model}.{name}: sum={float(np.asarray(f).sum()):.6f}")
    np.savez(OUT / "model_trajectories.npz", **arrays)
    print(f"wrote {OUT / 'model_trajectories.npz'}")


def main() -> None:
    OUT.mkdir(parents=True, exist_ok=True)
    capture_kernel_configs()
    capture_model_trajectories()


if __name__ == "__main__":
    main()
