#!/usr/bin/env bash
# Launch a multi-host simulation on a Cloud TPU pod slice.
#
# The reference ships per-machine HPC launch scripts (Summit/Crusher/
# Perlmutter jsrun/srun wrappers, scripts/*.sh); the TPU-native analog is
# one command fanned out to every pod worker — JAX discovers the pod
# topology itself (GS_TPU_DISTRIBUTED=auto -> jax.distributed.initialize).
#
# Usage:
#   ./scripts/run_tpu_pod.sh <tpu-name> <zone> <config.toml>
#
# Requires: gcloud configured, the repo present at the same path on every
# worker (or use --worker=all scp first).

set -euo pipefail

TPU_NAME="${1:?tpu name}"
ZONE="${2:?zone}"
CONFIG="${3:?config.toml}"
REPO_DIR="${REPO_DIR:-$(pwd)}"

gcloud compute tpus tpu-vm ssh "${TPU_NAME}" --zone "${ZONE}" --worker=all \
  --command "cd $(printf %q "${REPO_DIR}") && GS_TPU_DISTRIBUTED=auto python3 gray-scott.py $(printf %q "${CONFIG}")"
