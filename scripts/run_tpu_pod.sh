#!/usr/bin/env bash
# Launch a multi-host simulation on a Cloud TPU pod slice.
#
# The reference ships per-machine HPC launch scripts (Summit/Crusher/
# Perlmutter jsrun/srun wrappers, scripts/*.sh); the TPU-native analog is
# one command fanned out to every pod worker — JAX discovers the pod
# topology itself (GS_TPU_DISTRIBUTED=auto -> jax.distributed.initialize).
#
# Usage:
#   ./scripts/run_tpu_pod.sh <tpu-name> <zone> <config.toml>
#
# Tuning environment set locally (GS_FUSE, GS_TPU_STATS, GS_TPU_PROFILE,
# XLA_FLAGS, LIBTPU_INIT_ARGS, ...) is forwarded to every worker — the
# per-topology wrappers in scripts/pod/ set these before delegating here.
#
# Requires: gcloud configured, the repo present at the same path on every
# worker (or use --worker=all scp first).

set -euo pipefail

TPU_NAME="${1:?tpu name}"
ZONE="${2:?zone}"
CONFIG="${3:?config.toml}"
REPO_DIR="${REPO_DIR:-$(pwd)}"

# Forward the framework's tuning env vars into the remote command.
FWD=""
while IFS='=' read -r name value; do
  case "${name}" in
    GS_*|XLA_FLAGS|LIBTPU_INIT_ARGS|JAX_TRACEBACK_FILTERING)
      FWD+="${name}=$(printf %q "${value}") "
      ;;
  esac
done < <(env)

gcloud compute tpus tpu-vm ssh "${TPU_NAME}" --zone "${ZONE}" --worker=all \
  --command "cd $(printf %q "${REPO_DIR}") && ${FWD}GS_TPU_DISTRIBUTED=auto python3 gray-scott.py $(printf %q "${CONFIG}")"
