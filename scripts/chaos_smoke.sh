#!/usr/bin/env bash
# Chaos smoke test, eleven scenarios (1-3 against one uninterrupted
# solo reference run, 4 against an uninterrupted ensemble run, 5
# elastic — resume on a DIFFERENT mesh / member count than the kill,
# 6 serve — a worker killed mid-batch under the service front door,
# 7 integrity — silent checkpoint corruption survived by replica
# failover, 8 precision — lossy output resumed from an exact
# checkpoint, 9 fleet — a front-door replica AND a leaseholding
# worker process SIGKILLed mid-load under the distributed fleet,
# 10 serve elastic — live in-job grow+shrink reshapes under load with
# a worker SIGKILLed mid-reshape, 11 SDC — a device silently computing
# wrong answers is caught, attributed, and quarantined):
#
#   1. injected preemption at a pseudo-random step -> supervised
#      restart -> all stores byte-identical; runs with full
#      observability armed (GS_TRACE/GS_EVENTS/GS_METRICS) so the
#      byte-identity assertion doubles as the obs-transparency
#      contract, then greps the event stream for the injected fault
#      kind and validates the artifacts with gs_report.py --check;
#   2. injected driver hang at a pseudo-random step -> watchdog trips
#      (stack dump in the journal) -> supervised restart -> all stores
#      byte-identical;
#   3. real SIGTERM mid-run -> graceful boundary checkpoint -> exit 75
#      -> supervised relaunch auto-resumes from the journal marker ->
#      output stores byte-identical (the checkpoint store additionally
#      holds the off-schedule grace entry, asserted separately);
#   4. ensemble edition: injected preemption mid-sweep of a 2-member
#      batched ensemble -> supervised restart from the member-indexed
#      checkpoint quorum -> every member store byte-identical;
#   5. elastic resharding (docs/RESHARD.md): SIGTERM a supervised
#      (2,2,2) run mid-flight -> graceful checkpoint + exit 75 ->
#      supervised relaunch on a 4-device (1,2,2) replacement mesh
#      auto-resumes across the shape change (reshard event on
#      GS_EVENTS, gs_report.py --check validates) with stores
#      value-identical to the uninterrupted (2,2,2) run; then the
#      scenario-4 ensemble wreckage is resumed GROWN 2 -> 3 members on
#      the (2,2,2,1)-member layout, surviving member stores
#      byte-identical, the new member joining at the resume step;
#   6. simulation-as-a-service (docs/SERVICE.md): three jobs packed
#      onto one batched launch, GS_SERVE_CHAOS kills the worker
#      mid-batch -> scheduler requeues -> relaunch resumes from the
#      member-store checkpoint quorum -> every member store
#      byte-identical to an uninterrupted service run; the merged
#      event stream (job_* lifecycle kinds included) validates via
#      gs_report.py --check;
#   8. lossy output + exact checkpoints (docs/PRECISION.md): a
#      supervised run with the 8-bit snapshot codec armed
#      (GS_SNAPSHOT_BITS=8 — uint8 payloads in gs.bp) is preempted
#      mid-run and auto-resumes from its EXACT-precision checkpoint ->
#      the compressed output store and the .vti mirror are
#      byte-identical to an uninterrupted lossy run, proving the
#      codec's determinism and that checkpoints stayed exact;
#   7. data integrity (docs/RESILIENCE.md "Data integrity"): a
#      ckpt_corrupt fault flips a payload byte in the PRIMARY
#      checkpoint replica's freshly-durable entry mid-run, a later
#      preemption forces a restore -> verify-on-read detects the CRC
#      mismatch -> the restore fails over to the .r1 mirror
#      (replica_failover on GS_EVENTS, validated by gs_report.py
#      --check) -> final output stores byte-identical to an
#      uninterrupted run, and the surviving mirror byte-identical to
#      the uninterrupted primary;
#   9. distributed fleet (docs/SERVICE.md "the distributed fleet"):
#      two front-door replicas + two worker processes share one
#      GS_SERVE_FLEET_DIR; one front door AND the worker holding a
#      batch lease are SIGKILLed mid-load -> the surviving replica's
#      reaper expires the lease, the surviving worker adopts the
#      resume entry, and EVERY accepted job completes; re-requesting
#      a completed JobSpec is served from the content-addressed result
#      cache with cache="hit" provenance and a byte-identical store;
#      the merged multi-rank event stream (worker_join/worker_lost/
#      job_failover/cache_* kinds included) validates via
#      gs_report.py --check;
#  10. serve elastic reshapes (docs/RESHARD.md "In-job reshapes",
#      docs/SERVICE.md "Elastic capacity"): a fleet (one front door,
#      two workers) under packed load; one RUNNING batch is steered
#      through a live shrink -> grow cycle via the ``reshape/<batch>``
#      KV relay (no kill, no checkpoint round-trip — reshard events
#      with device-path provenance land on the merged stream), while
#      the OTHER batch's leaseholding worker is SIGKILLed the moment
#      its own reshape request lands; the orphaned request dies with
#      the lease (the reaper deletes the doc), the surviving worker
#      adopts the resume, and ALL accepted jobs complete with stores
#      identical to an uninterrupted no-reshape service run — raw
#      bytes for the globally-written .vtk series, served-value
#      bitwise for the mesh-changed .bp stores (the scenario-5
#      equality fine print);
#  11. silent data corruption (docs/RESILIENCE.md "Silent data
#      corruption"): two seeded kind=sdc faults — compute-path
#      bitflips into a step INPUT on one named device, the class the
#      at-rest CRC layer cannot see — under GS_SDC_CHECK=spot and a
#      supervisor; the boundary replay detects each mismatch with
#      device attribution (sdc_mismatch on GS_EVENTS), the first
#      recovery resumes from the last VERIFIED checkpoint, the
#      same-device repeat QUARANTINES the chip (device_quarantined +
#      GS_DEVICE_BLOCKLIST) and the restart rebuilds the mesh on the
#      survivors; the finished stores are content-identical to a
#      fault-free screened run (served-value bitwise for gs.bp — the
#      post-quarantine mesh changes the chunk layout — raw bytes for
#      the globally-written .vtk series).
#
# The fault steps are derived deterministically from a seed (crc32,
# printed below), so a failing run is replayable bit-for-bit:
#
#   ./scripts/chaos_smoke.sh [seed]     # default seed 0, or $CHAOS_SEED
#
# The fast fixed-step variants of these scenarios run in tier-1 as
# tests/functional/test_supervisor.py; this script is the
# operator-facing knob-twister (vary the seed, watch the journal).
# See docs/RESILIENCE.md for the failure taxonomy and knobs.

set -euo pipefail

SEED="${1:-${CHAOS_SEED:-0}}"
REPO="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
WORK="$(mktemp -d)"
trap 'rm -rf "$WORK"' EXIT

STEPS=60
# Pseudo-random fault steps in [5, 54] — strictly mid-run, printed so a
# failure is reproducible by re-running with the same seed.
PREEMPT="$(python3 -c "import zlib; print(5 + zlib.crc32(b'chaos:${SEED}') % ($STEPS - 10))")"
HANG="$(python3 -c "import zlib; print(5 + zlib.crc32(b'hang:${SEED}') % ($STEPS - 10))")"
echo "chaos_smoke: seed=${SEED} -> injected preemption at step ${PREEMPT}, hang at step ${HANG}"

write_config() {
  cat > "$1/config.toml" <<EOF
L = 32
Du = 0.2
Dv = 0.1
F = 0.02
k = 0.048
dt = 1.0
plotgap = 10
steps = ${STEPS}
noise = 0.1
output = "gs.bp"
checkpoint = true
checkpoint_freq = 20
checkpoint_output = "ckpt.bp"
precision = "Float32"
backend = "CPU"
kernel_language = "Plain"
verbose = true
EOF
}

run() {
  # Fixed vars first, scenario vars last: a scenario may override
  # XLA_FLAGS (device count) / GS_TPU_MESH_DIMS for the elastic
  # reshard scenario below.
  local dir="$1"; shift
  (
    cd "$dir"
    env JAX_PLATFORMS=cpu \
      XLA_FLAGS="--xla_force_host_platform_device_count=8" \
      PYTHONPATH="${REPO}${PYTHONPATH:+:${PYTHONPATH}}" \
      "$@" \
      python3 "${REPO}/gray-scott.py" config.toml
  )
}

assert_stores() {
  local dir="$1"; shift
  for store in "$@"; do
    if ! diff -r "$WORK/full/$store" "$dir/$store" > /dev/null; then
      echo "chaos_smoke: FAIL — $store differs from the uninterrupted run" >&2
      diff -rq "$WORK/full/$store" "$dir/$store" >&2 || true
      exit 1
    fi
  done
}

mkdir -p "$WORK/full" "$WORK/sup" "$WORK/hang" "$WORK/term"
for d in full sup hang term; do write_config "$WORK/$d"; done

echo "chaos_smoke: uninterrupted reference run..."
run "$WORK/full" > "$WORK/full.log" 2>&1

echo "chaos_smoke: [1/5] supervised run with injected preemption (obs armed)..."
# Full observability rides along (docs/OBSERVABILITY.md): the store
# byte-identity assertion below doubles as the obs-on/off bitwise
# contract, and the artifacts are schema-validated afterwards.
run "$WORK/sup" \
  GS_SUPERVISE=1 \
  GS_MAX_RESTARTS=5 \
  GS_RESTART_BACKOFF_S=0.05 \
  GS_FAULTS="step=${PREEMPT}:kind=preempt" \
  GS_TRACE="$WORK/sup/trace.json" \
  GS_EVENTS="$WORK/sup/events.jsonl" \
  GS_METRICS="$WORK/sup/metrics.jsonl" \
  GS_NUMERICS=boundary \
  GS_XSTATS=1 \
  GS_TPU_STATS="$WORK/sup/stats.json" \
  > "$WORK/sup.log" 2>&1

grep -a "supervisor:" "$WORK/sup.log" > /dev/null || {
  echo "chaos_smoke: FAIL — the supervisor never recovered anything" >&2
  exit 1
}
assert_stores "$WORK/sup" gs.bp gs.vtk ckpt.bp

# The unified event stream must carry the injected fault kind AND its
# recovery on one timeline, and the trace/events files must validate
# against the Chrome-trace / event schemas (gs_report.py --check).
grep -aq '"fault": "preempt"' "$WORK/sup/events.jsonl" || {
  echo "chaos_smoke: FAIL — injected preempt missing from the event stream" >&2
  exit 1
}
grep -aq '"kind": "recovery"' "$WORK/sup/events.jsonl" || {
  echo "chaos_smoke: FAIL — recovery decision missing from the event stream" >&2
  exit 1
}
# Device-side flight recorder (docs/OBSERVABILITY.md): the in-graph
# numerics probes and executable analytics ride along (the store
# byte-identity above doubles as THEIR transparency contract too);
# both record kinds must be on the stream and validate.
grep -aq '"kind": "numerics"' "$WORK/sup/events.jsonl" || {
  echo "chaos_smoke: FAIL — no numerics records on the event stream" >&2
  exit 1
}
grep -aq '"kind": "executable"' "$WORK/sup/events.jsonl" || {
  echo "chaos_smoke: FAIL — no executable records on the event stream" >&2
  exit 1
}
PYTHONPATH="${REPO}${PYTHONPATH:+:${PYTHONPATH}}" python3 \
  "${REPO}/scripts/gs_report.py" --check \
  --trace "$WORK/sup/trace.json" --events "$WORK/sup/events.jsonl" \
  --stats "$WORK/sup/stats.json" || {
  echo "chaos_smoke: FAIL — gs_report.py --check rejected the obs artifacts" >&2
  exit 1
}

# Perf-regression sentinel (benchmarks/regression_gate.py) over this
# run's own artifact: distill the chaos run's step-latency stats into
# one artifact row, gate it against itself-as-history (plumbing smoke —
# must pass), then assert a synthetic 2x slowdown flips the exit code
# and names the culprit metric. The committed-history comparison runs
# in tier-1 and tune_sweep --calibrate; this exercises the tripwire
# end to end on freshly-measured data.
PYTHONPATH="${REPO}${PYTHONPATH:+:${PYTHONPATH}}" python3 - \
  "$WORK/sup/stats.json" "$WORK/sup/chaos_perf.jsonl" <<'EOF'
import json, sys

stats = json.load(open(sys.argv[1]))
hist = next(h for h in stats["metrics"]["histograms"]
            if h["name"] == "step_latency_us")
cfg = stats["config"]
row = {
    "ab": "chaos_smoke", "platform": "cpu", "model": cfg["model"],
    "L": stats["L"], "mesh": cfg["mesh_dims"],
    "devices": cfg["n_devices"], "kernel": cfg["kernel_language"],
    "median_us_per_step": hist["p50"],
}
with open(sys.argv[2], "w") as f:
    for _ in range(4):  # 3 history rows + the judged row (--self)
        f.write(json.dumps(row) + "\n")
EOF
PYTHONPATH="${REPO}${PYTHONPATH:+:${PYTHONPATH}}" python3 \
  "${REPO}/benchmarks/regression_gate.py" \
  --fresh "$WORK/sup/chaos_perf.jsonl" --history --self || {
  echo "chaos_smoke: FAIL — regression_gate flagged an unregressed run" >&2
  exit 1
}
if PYTHONPATH="${REPO}${PYTHONPATH:+:${PYTHONPATH}}" python3 \
  "${REPO}/benchmarks/regression_gate.py" \
  --fresh "$WORK/sup/chaos_perf.jsonl" --history --self \
  --inject-slowdown 2 2> "$WORK/sup/gate2x.log"; then
  echo "chaos_smoke: FAIL — regression_gate missed the injected 2x slowdown" >&2
  exit 1
fi
grep -aq "median_us_per_step" "$WORK/sup/gate2x.log" || {
  echo "chaos_smoke: FAIL — regression_gate did not name the culprit metric" >&2
  exit 1
}

echo "chaos_smoke: [2/5] supervised run with injected hang (watchdog)..."
run "$WORK/hang" \
  GS_SUPERVISE=1 \
  GS_MAX_RESTARTS=5 \
  GS_RESTART_BACKOFF_S=0.05 \
  GS_WATCHDOG=on \
  GS_WATCHDOG_STEP_ROUND_S=3 \
  GS_HANG_BOUND_S=60 \
  GS_FAULTS="step=${HANG}:kind=hang" \
  > "$WORK/hang.log" 2>&1

grep -a "supervisor: hang" "$WORK/hang.log" > /dev/null || {
  echo "chaos_smoke: FAIL — the watchdog never classified the hang" >&2
  exit 1
}
grep -aq '"event": "hang"' "$WORK/hang/gs.bp.faults.jsonl" || {
  echo "chaos_smoke: FAIL — no hang stack dump in the journal" >&2
  exit 1
}
assert_stores "$WORK/hang" gs.bp gs.vtk ckpt.bp

echo "chaos_smoke: [3/5] SIGTERM mid-run -> graceful checkpoint -> resume..."
# Park the run at a deterministic boundary with an unwatched injected
# stall, SIGTERM it there (the injected-hang journal line is fsynced
# before the stall starts, so polling it makes the timing exact).
(
  cd "$WORK/term"
  # exec: the SIGTERM below must land on python itself, not a wrapper
  # subshell that would die 143 and orphan the run.
  exec env GS_SUPERVISE=1 GS_WATCHDOG=off GS_HANG_BOUND_S=60 \
      GS_FAULTS="step=${HANG}:kind=hang" \
      JAX_PLATFORMS=cpu \
      XLA_FLAGS="--xla_force_host_platform_device_count=8" \
      PYTHONPATH="${REPO}${PYTHONPATH:+:${PYTHONPATH}}" \
      python3 "${REPO}/gray-scott.py" config.toml
) > "$WORK/term.log" 2>&1 &
TERM_PID=$!
for _ in $(seq 1 600); do
  grep -aq '"kind": "hang"' "$WORK/term/gs.bp.faults.jsonl" 2>/dev/null && break
  sleep 0.1
done
kill -TERM "$TERM_PID"
RC=0; wait "$TERM_PID" || RC=$?
if [ "$RC" -ne 75 ]; then
  echo "chaos_smoke: FAIL — SIGTERM run exited $RC, want 75 (EXIT_PREEMPTED)" >&2
  tail -n 20 "$WORK/term.log" >&2
  exit 1
fi
grep -aq '"event": "graceful_shutdown"' "$WORK/term/gs.bp.faults.jsonl" || {
  echo "chaos_smoke: FAIL — no graceful_shutdown marker journaled" >&2
  exit 1
}
# A plain supervised relaunch must auto-resume from the marker.
run "$WORK/term" GS_SUPERVISE=1 > "$WORK/term_resume.log" 2>&1
grep -a "resuming after graceful_shutdown" "$WORK/term_resume.log" > /dev/null || {
  echo "chaos_smoke: FAIL — relaunch did not auto-resume" >&2
  exit 1
}
# Output stores byte-identical; the checkpoint store additionally holds
# the off-schedule grace entry, so assert it is a superset ending on
# the schedule instead of diffing bytes.
assert_stores "$WORK/term" gs.bp gs.vtk
PYTHONPATH="${REPO}${PYTHONPATH:+:${PYTHONPATH}}" python3 - "$WORK/term/ckpt.bp" <<'EOF'
import sys
from grayscott_jl_tpu.io.bplite import BpReader

r = BpReader(sys.argv[1])
steps = [int(r.get("step", step=i)) for i in range(r.num_steps())]
assert steps[-1] == 60 and sorted(set(steps)) == steps, steps
assert set(range(20, 61, 20)) <= set(steps), steps
EOF

echo "chaos_smoke: [4/5] ensemble preempt mid-sweep -> auto-resume..."
write_ensemble_config() {
  write_config "$1"
  cat >> "$1/config.toml" <<'EOF'

[ensemble]
presets = ["spots", "chaos"]
EOF
}
mkdir -p "$WORK/ensfull" "$WORK/enssup"
for d in ensfull enssup; do write_ensemble_config "$WORK/$d"; done

run "$WORK/ensfull" > "$WORK/ensfull.log" 2>&1
run "$WORK/enssup" \
  GS_SUPERVISE=1 \
  GS_MAX_RESTARTS=5 \
  GS_RESTART_BACKOFF_S=0.05 \
  GS_FAULTS="step=${PREEMPT}:kind=preempt" \
  > "$WORK/enssup.log" 2>&1

grep -a "supervisor:" "$WORK/enssup.log" > /dev/null || {
  echo "chaos_smoke: FAIL — the ensemble supervisor never recovered" >&2
  exit 1
}
# Per-member byte-identity: every member-indexed store of the faulted
# run must match the uninterrupted ensemble's.
for m in m00 m01; do
  for store in "gs.${m}.bp" "gs.${m}.vtk" "ckpt.${m}.bp"; do
    if ! diff -r "$WORK/ensfull/$store" "$WORK/enssup/$store" > /dev/null; then
      echo "chaos_smoke: FAIL — ensemble $store differs after resume" >&2
      diff -rq "$WORK/ensfull/$store" "$WORK/enssup/$store" >&2 || true
      exit 1
    fi
  done
done

echo "chaos_smoke: [5/5] elastic — SIGTERM on (2,2,2), resume on (1,2,2)..."
# Value-level store identity: a store that changed mesh mid-life frames
# its later steps in the new decomposition's blocks, so the assertion
# is on what the store SERVES — attributes and every step's assembled
# global arrays, bitwise (docs/RESHARD.md "Equality fine print"). The
# VTK series is written globally and stays raw-byte-identical.
compare_bp() {
  PYTHONPATH="${REPO}${PYTHONPATH:+:${PYTHONPATH}}" python3 - "$1" "$2" <<'EOF'
import sys
import numpy as np
from grayscott_jl_tpu.io.bplite import BpReader

a, b = BpReader(sys.argv[1]), BpReader(sys.argv[2])
assert a.attributes() == b.attributes()
assert a.num_steps() == b.num_steps(), (a.num_steps(), b.num_steps())
for i in range(a.num_steps()):
    for name in a.available_variables():
        x = np.asarray(a.get(name, step=i))
        y = np.asarray(b.get(name, step=i))
        assert x.dtype == y.dtype and np.array_equal(x, y), (name, i)
EOF
}

mkdir -p "$WORK/elastic"
write_config "$WORK/elastic"
(
  cd "$WORK/elastic"
  exec env GS_SUPERVISE=1 GS_WATCHDOG=off GS_HANG_BOUND_S=60 \
      GS_FAULTS="step=${HANG}:kind=hang" \
      JAX_PLATFORMS=cpu \
      XLA_FLAGS="--xla_force_host_platform_device_count=8" \
      PYTHONPATH="${REPO}${PYTHONPATH:+:${PYTHONPATH}}" \
      python3 "${REPO}/gray-scott.py" config.toml
) > "$WORK/elastic.log" 2>&1 &
EL_PID=$!
for _ in $(seq 1 600); do
  grep -aq '"kind": "hang"' "$WORK/elastic/gs.bp.faults.jsonl" 2>/dev/null && break
  sleep 0.1
done
kill -TERM "$EL_PID"
RC=0; wait "$EL_PID" || RC=$?
if [ "$RC" -ne 75 ]; then
  echo "chaos_smoke: FAIL — elastic SIGTERM run exited $RC, want 75" >&2
  exit 1
fi
# Replacement slice: 4 devices shaped (1,2,2). A plain supervised
# relaunch auto-resumes from the journal marker ACROSS the shape
# change; the reshard event lands on GS_EVENTS.
run "$WORK/elastic" \
  GS_SUPERVISE=1 \
  XLA_FLAGS="--xla_force_host_platform_device_count=4" \
  GS_TPU_MESH_DIMS="1,2,2" \
  GS_EVENTS="$WORK/elastic/events.jsonl" \
  > "$WORK/elastic_resume.log" 2>&1
grep -a "Resharded restore" "$WORK/elastic_resume.log" > /dev/null || {
  echo "chaos_smoke: FAIL — the relaunch never announced the reshard" >&2
  exit 1
}
compare_bp "$WORK/full/gs.bp" "$WORK/elastic/gs.bp" || {
  echo "chaos_smoke: FAIL — gs.bp values differ after the (1,2,2) resume" >&2
  exit 1
}
if ! diff -r "$WORK/full/gs.vtk" "$WORK/elastic/gs.vtk" > /dev/null; then
  echo "chaos_smoke: FAIL — gs.vtk differs after the (1,2,2) resume" >&2
  exit 1
fi
grep -aq '"kind": "reshard"' "$WORK/elastic/events.jsonl" || {
  echo "chaos_smoke: FAIL — no reshard event on GS_EVENTS" >&2
  exit 1
}
PYTHONPATH="${REPO}${PYTHONPATH:+:${PYTHONPATH}}" python3 \
  "${REPO}/scripts/gs_report.py" --check \
  --events "$WORK/elastic/events.jsonl" || {
  echo "chaos_smoke: FAIL — gs_report.py --check rejected the reshard events" >&2
  exit 1
}

echo "chaos_smoke: [5/5] elastic — ensemble grow 2 -> 3 members..."
mkdir -p "$WORK/ensgrow"
write_ensemble_config "$WORK/ensgrow"
# Kill a fresh 2-member run unsupervised mid-sweep, then resume the
# wreckage GROWN to 3 members on the (2,2,2,1)-member layout.
run "$WORK/ensgrow" GS_FAULTS="step=${PREEMPT}:kind=preempt" \
  > "$WORK/ensgrow.log" 2>&1 || true
# restart must precede the [ensemble] table (top-level TOML key)
sed -i 's/^checkpoint = true$/checkpoint = true\nrestart = true/' \
  "$WORK/ensgrow/config.toml"
sed -i 's/presets = \["spots", "chaos"\]/presets = ["spots", "chaos", "waves"]/' \
  "$WORK/ensgrow/config.toml"
run "$WORK/ensgrow" > "$WORK/ensgrow_resume.log" 2>&1
grep -a "Restarted 3 ensemble members" "$WORK/ensgrow_resume.log" > /dev/null || {
  echo "chaos_smoke: FAIL — the grown ensemble never restored 3 members" >&2
  exit 1
}
for m in m00 m01; do
  for store in "gs.${m}.bp" "gs.${m}.vtk" "ckpt.${m}.bp"; do
    if ! diff -r "$WORK/ensfull/$store" "$WORK/ensgrow/$store" > /dev/null; then
      echo "chaos_smoke: FAIL — ensemble $store differs after grow-resume" >&2
      exit 1
    fi
  done
done
[ -d "$WORK/ensgrow/gs.m02.bp" ] || {
  echo "chaos_smoke: FAIL — the grown member wrote no store" >&2
  exit 1
}

echo "chaos_smoke: [6/6] serve — worker kill mid-batch, scheduler requeue..."
# Simulation-as-a-service edition (docs/SERVICE.md): three jobs packed
# onto one batched launch, GS_SERVE_CHAOS kills the worker mid-batch
# (preempt at the seeded step), the scheduler requeues the batch, the
# relaunch resumes from the member-store checkpoint quorum — and every
# member store must be byte-identical to the same jobs served by an
# UNinterrupted service. The merged event stream (job_* lifecycle +
# run events) must validate via gs_report.py --check.
mkdir -p "$WORK/serve"
PYTHONPATH="${REPO}${PYTHONPATH:+:${PYTHONPATH}}" \
  JAX_PLATFORMS=cpu \
  CHAOS_PREEMPT="$PREEMPT" \
  SERVE_WORK="$WORK/serve" \
  python3 - <<'EOF'
import filecmp, glob, json, os, time, urllib.request

work = os.environ["SERVE_WORK"]
preempt = max(4, int(os.environ["CHAOS_PREEMPT"]) % 20)
os.environ["GS_SERVE_PORT"] = "0"
os.environ["GS_SERVE_PACK_MAX"] = "4"
os.environ["GS_SERVE_PACK_WINDOW_S"] = "0.2"
os.environ["GS_EVENTS"] = os.path.join(work, "events.jsonl")

from grayscott_jl_tpu.serve.scheduler import resolve_serve_config
from grayscott_jl_tpu.serve.server import ServeService


def post(base, path, payload):
    req = urllib.request.Request(
        base + path, data=json.dumps(payload).encode()
    )
    with urllib.request.urlopen(req) as r:
        return json.loads(r.read())


def get(base, path):
    with urllib.request.urlopen(base + path) as r:
        return json.loads(r.read())


SPECS = [
    {
        "tenant": "chaos", "model": "grayscott", "L": 16, "steps": 24,
        "plotgap": 8, "checkpoint_freq": 8, "dt": 1.0, "noise": 0.1,
        "seed": 11 + i,
        "params": {"F": 0.03 + 0.005 * i, "k": 0.062,
                   "Du": 0.2, "Dv": 0.1},
    }
    for i in range(3)
]


def run_service(state_dir, chaos=""):
    os.environ["GS_SERVE_STATE_DIR"] = os.path.join(work, state_dir)
    os.environ["GS_SERVE_CHAOS"] = chaos
    svc = ServeService(resolve_serve_config()).start()
    base = f"http://127.0.0.1:{svc.port}"
    jobs = [post(base, "/v1/jobs", s)["job"] for s in SPECS]
    deadline = time.time() + 300
    while time.time() < deadline:
        st = [get(base, f"/v1/jobs/{j}")["state"] for j in jobs]
        if all(s in ("complete", "failed") for s in st):
            break
        time.sleep(0.3)
    stores = [get(base, f"/v1/jobs/{j}")["store"] for j in jobs]
    svc.close()
    assert all(s == "complete" for s in st), f"job states: {st}"
    return stores


chaos_stores = run_service("killed", chaos=f"step={preempt}:kind=preempt")
ref_stores = run_service("ref")

events = [json.loads(l) for l in
          open(os.path.join(work, "events.jsonl"))]
kinds = {e["kind"] for e in events}
assert "job_requeued" in kinds, f"no job_requeued on the stream: {kinds}"
assert "injected" in kinds, "the worker-kill fault never fired"

for a, b in zip(chaos_stores, ref_stores):
    for suffix in ("", ".vtk"):
        pa, pb = a.replace(".bp", suffix or ".bp"), b.replace(
            ".bp", suffix or ".bp")
        cmp = filecmp.dircmp(pa, pb)
        same = not (cmp.left_only or cmp.right_only or cmp.diff_files)
        assert same and all(
            open(os.path.join(pa, f), "rb").read()
            == open(os.path.join(pb, f), "rb").read()
            for f in cmp.common_files
        ), f"{pa} differs from uninterrupted {pb}"
print(f"serve chaos: worker killed at step {preempt}, requeued, "
      f"{len(chaos_stores)} member stores byte-identical")
EOF
PYTHONPATH="${REPO}${PYTHONPATH:+:${PYTHONPATH}}" python3 \
  "${REPO}/scripts/gs_report.py" --check \
  --events "$WORK/serve/events.jsonl" || {
  echo "chaos_smoke: FAIL — gs_report.py --check rejected the serve events" >&2
  exit 1
}

echo "chaos_smoke: [7/7] integrity — ckpt corruption -> replica failover..."
# The fault must corrupt the entry the restore will read: checkpoint
# boundaries land at 20/40, so a corrupt step in [31, 40] fires at
# boundary 40 (right after that entry became durable — depth 0 keeps
# the write inline) and the preemption at 45 forces a restore OF that
# entry. Seeded like the other scenarios, printed for replay.
CORRUPT="$(python3 -c "import zlib; print(31 + zlib.crc32(b'ckpt:${SEED}') % 10)")"
echo "chaos_smoke: seed=${SEED} -> ckpt_corrupt at step ${CORRUPT}, preempt at 45"
mkdir -p "$WORK/integref" "$WORK/integ"
for d in integref integ; do write_config "$WORK/$d"; done
# Both runs (reference included) share the integrity env: replicated
# checkpoints + full verify, so the byte-identity assertion compares
# like with like — integrity sidecars and device checksums included.
run "$WORK/integref" \
  GS_CKPT_REPLICAS=2 \
  GS_CKPT_VERIFY=full \
  GS_ASYNC_IO_DEPTH=0 \
  > "$WORK/integref.log" 2>&1
run "$WORK/integ" \
  GS_SUPERVISE=1 \
  GS_MAX_RESTARTS=5 \
  GS_RESTART_BACKOFF_S=0.05 \
  GS_CKPT_REPLICAS=2 \
  GS_CKPT_VERIFY=full \
  GS_ASYNC_IO_DEPTH=0 \
  GS_EVENTS="$WORK/integ/events.jsonl" \
  GS_FAULTS="step=${CORRUPT}:kind=ckpt_corrupt;step=45:kind=preempt" \
  > "$WORK/integ.log" 2>&1

grep -aq '"kind": "replica_failover"' "$WORK/integ/events.jsonl" || {
  echo "chaos_smoke: FAIL — the restore never failed over to the mirror" >&2
  exit 1
}
grep -aq 'CRC mismatch' "$WORK/integ/events.jsonl" || {
  echo "chaos_smoke: FAIL — no CRC-mismatch detection on the event stream" >&2
  exit 1
}
# Output stores byte-identical to the uninterrupted integrity run; the
# surviving mirror byte-identical to the uninterrupted primary (the
# corrupted primary differs by exactly the injected byte).
for store in gs.bp gs.vtk; do
  if ! diff -r "$WORK/integref/$store" "$WORK/integ/$store" > /dev/null; then
    echo "chaos_smoke: FAIL — $store differs after the corruption failover" >&2
    exit 1
  fi
done
if ! diff -r "$WORK/integref/ckpt.bp" "$WORK/integ/ckpt.bp.r1" > /dev/null; then
  echo "chaos_smoke: FAIL — surviving mirror differs from uninterrupted primary" >&2
  exit 1
fi
PYTHONPATH="${REPO}${PYTHONPATH:+:${PYTHONPATH}}" python3 \
  "${REPO}/scripts/gs_report.py" --check \
  --events "$WORK/integ/events.jsonl" || {
  echo "chaos_smoke: FAIL — gs_report.py --check rejected the integrity events" >&2
  exit 1
}

echo "chaos_smoke: [8/8] precision — lossy output + preempt -> exact-checkpoint resume..."
# Same seeded preemption as scenario 1, now with the 8-bit snapshot
# codec armed on BOTH runs: the reference is the uninterrupted lossy
# run, and byte-identity of the uint8 store proves the quantized
# output is deterministic across a restart from the EXACT checkpoint.
mkdir -p "$WORK/lossyref" "$WORK/lossy"
for d in lossyref lossy; do write_config "$WORK/$d"; done
run "$WORK/lossyref" \
  GS_SNAPSHOT_BITS=8 \
  > "$WORK/lossyref.log" 2>&1
run "$WORK/lossy" \
  GS_SUPERVISE=1 \
  GS_MAX_RESTARTS=5 \
  GS_RESTART_BACKOFF_S=0.05 \
  GS_SNAPSHOT_BITS=8 \
  GS_EVENTS="$WORK/lossy/events.jsonl" \
  GS_FAULTS="step=${PREEMPT}:kind=preempt" \
  > "$WORK/lossy.log" 2>&1
# The output store really is compressed (uint8 payloads)...
grep -aq '"uint8"' "$WORK/lossy/gs.bp/md.json" || {
  echo "chaos_smoke: FAIL — lossy store carries no uint8 payloads" >&2
  exit 1
}
# ...and the checkpoint really is exact (float32 variables, no codec).
python3 - "$WORK/lossy/ckpt.bp/md.json" <<'EOF'
import json, sys
md = json.load(open(sys.argv[1]))
assert md["variables"]["u"]["dtype"] == "float32", md["variables"]["u"]
assert "snapshot_codec" not in md.get("attributes", {}), "ckpt got the codec"
EOF
for store in gs.bp gs.vtk; do
  if ! diff -r "$WORK/lossyref/$store" "$WORK/lossy/$store" > /dev/null; then
    echo "chaos_smoke: FAIL — lossy $store differs after the preempt resume" >&2
    exit 1
  fi
done
grep -aq '"fault": "preempt"' "$WORK/lossy/events.jsonl" || {
  echo "chaos_smoke: FAIL — injected preempt missing from the lossy event stream" >&2
  exit 1
}

echo "chaos_smoke: [9/9] fleet — front door + worker SIGKILLed mid-load, cache replay..."
# Distributed-fleet edition (ISSUE 17): the kill is a real SIGKILL of
# two of the four fleet PROCESSES — no in-process chaos hook — so the
# recovery path is lease expiry -> reaper fail-over -> resume adoption
# by the surviving worker, all through the shared fleet dir.
mkdir -p "$WORK/fleet"
PYTHONPATH="${REPO}${PYTHONPATH:+:${PYTHONPATH}}" \
  JAX_PLATFORMS=cpu \
  REPO_DIR="$REPO" \
  FLEET_WORK="$WORK/fleet" \
  python3 - <<'EOF'
import filecmp, json, os, shutil, signal, subprocess, sys, time
import urllib.request

repo = os.environ["REPO_DIR"]
work = os.environ["FLEET_WORK"]
fleet_dir = os.path.join(work, "fleet")

sys.path.insert(0, repo)
from grayscott_jl_tpu.serve.cluster import FleetKV


def member_env(rank, workers):
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["GS_SERVE_FLEET_DIR"] = fleet_dir
    env["GS_SERVE_FLEET_RANK"] = str(rank)
    env["GS_SERVE_PORT"] = "0"
    env["GS_SERVE_WORKERS"] = str(workers)
    env["GS_SERVE_STATE_DIR"] = os.path.join(work, f"state{rank}")
    env["GS_SERVE_LEASE_TTL_S"] = "3.0"
    env["GS_SERVE_HEARTBEAT_S"] = "0.5"
    env["GS_SERVE_PACK_MAX"] = "2"
    env["GS_SERVE_PACK_WINDOW_S"] = "0.1"
    env["GS_SERVE_SUPERVISE"] = "0"
    env["GS_EVENTS"] = os.path.join(work, "events.jsonl")
    env["GS_CKPT_REPLICAS"] = "2"
    return env


def post(base, path, payload):
    req = urllib.request.Request(
        base + path, data=json.dumps(payload).encode()
    )
    with urllib.request.urlopen(req) as r:
        return json.loads(r.read())


def get(base, path):
    with urllib.request.urlopen(base + path) as r:
        return json.loads(r.read())


def spec(i):
    return {
        "tenant": "chaos", "model": "grayscott", "L": 16, "steps": 24,
        "plotgap": 8, "checkpoint_freq": 8, "dt": 1.0, "noise": 0.1,
        "seed": 200 + i,
        "params": {"F": 0.03 + 0.002 * i, "k": 0.062,
                   "Du": 0.2, "Dv": 0.1},
    }


procs = []
for rank, role in ((0, "frontdoor"), (1, "frontdoor"),
                   (2, "worker"), (3, "worker")):
    args = [sys.executable, os.path.join(repo, "scripts", "gs_serve.py")]
    if role == "worker":
        args += ["--role", "worker"]
    procs.append(subprocess.Popen(
        args, env=member_env(rank, 1 if role == "worker" else 0),
        cwd=work,
        stdout=open(os.path.join(work, f"member{rank}.log"), "w"),
        stderr=subprocess.STDOUT,
    ))

kv = FleetKV(fleet_dir)
bases = {}
deadline = time.time() + 120
while time.time() < deadline and len(bases) < 2:
    for mid in kv.keys("members"):
        doc = kv.get(f"members/{mid}")
        if doc and doc.get("role") == "frontdoor" and doc.get("port"):
            bases[mid] = (f"http://{doc['host']}:{doc['port']}",
                          doc["pid"])
    time.sleep(0.2)
assert len(bases) == 2, f"front doors never announced: {bases}"
(base_a, pid_a), (base_b, pid_b) = sorted(bases.values())

jobs = [post(base_a if i % 2 == 0 else base_b,
             "/v1/jobs", spec(i))["job"] for i in range(4)]

victim_pid = None
deadline = time.time() + 120
while time.time() < deadline and victim_pid is None:
    for bid in kv.keys("leases"):
        lease = kv.get(f"leases/{bid}")
        mdoc = lease and kv.get(f"members/{lease['worker']}")
        if mdoc:
            victim_pid = mdoc["pid"]
            break
    time.sleep(0.05)
assert victim_pid is not None, "no worker ever took a lease"
os.kill(victim_pid, signal.SIGKILL)
os.kill(pid_b, signal.SIGKILL)

jobs += [post(base_a, "/v1/jobs", spec(i))["job"] for i in (4, 5)]

deadline = time.time() + 420
records = []
while time.time() < deadline:
    records = [get(base_a, f"/v1/jobs/{j}") for j in jobs]
    if all(r["state"] in ("complete", "failed") for r in records):
        break
    time.sleep(0.3)
states = [r["state"] for r in records]
assert states == ["complete"] * 6, f"fleet job states: {states}"

# Cached replay: the repeated JobSpec is terminal IN the submit
# response, names the same store, and the bytes are identical.
target = records[0]
snapshot = os.path.join(work, "snapshot.bp")
shutil.copytree(target["store"], snapshot)
body = post(base_a, "/v1/jobs", spec(0))
assert body["cache"] == "hit", body
assert body["state"] == "complete", body
assert body["store"] == target["store"], body
cmp = filecmp.dircmp(snapshot, body["store"])
assert not (cmp.left_only or cmp.right_only or cmp.diff_files), (
    f"cached store drifted: {cmp.diff_files}"
)
assert all(
    open(os.path.join(snapshot, f), "rb").read()
    == open(os.path.join(body["store"], f), "rb").read()
    for f in cmp.common_files
), "cached replay not byte-identical"

for p in procs:
    if p.poll() is None:
        p.send_signal(signal.SIGTERM)
for p in procs:
    try:
        p.wait(timeout=60)
    except subprocess.TimeoutExpired:
        p.kill()
print(f"fleet chaos: killed front door {pid_b} + worker {victim_pid} "
      f"mid-load; 6/6 jobs completed, cached replay byte-identical")
EOF
PYTHONPATH="${REPO}${PYTHONPATH:+:${PYTHONPATH}}" python3 \
  "${REPO}/scripts/gs_report.py" --check \
  --events "$WORK/fleet/events.jsonl" || {
  echo "chaos_smoke: FAIL — gs_report.py --check rejected the fleet events" >&2
  exit 1
}
grep -aq '"kind": "worker_lost"' "$WORK/fleet"/events.jsonl.rank* || {
  echo "chaos_smoke: FAIL — no worker_lost on the merged fleet stream" >&2
  exit 1
}
grep -aq '"kind": "cache_hit"' "$WORK/fleet"/events.jsonl.rank* || {
  echo "chaos_smoke: FAIL — no cache_hit on the merged fleet stream" >&2
  exit 1
}

echo "chaos_smoke: [10/10] serve elastic — live grow+shrink, worker SIGKILL mid-reshape..."
# The reshape relay is driven directly through the fleet KV (the same
# doc shape ClusterScheduler.request_reshape publishes) so the timing
# is deterministic; the elastic CONTROLLER policy itself is covered by
# tier-1 unit tests — this scenario proves the machinery under it: a
# live between-rounds reshape on a RUNNING packed batch, and the
# lease-reap cleanup of a request whose worker died mid-reshape.
mkdir -p "$WORK/elserve"
PYTHONPATH="${REPO}${PYTHONPATH:+:${PYTHONPATH}}" \
  JAX_PLATFORMS=cpu \
  REPO_DIR="$REPO" \
  ELSERVE_WORK="$WORK/elserve" \
  python3 - <<'EOF'
import filecmp, json, os, signal, subprocess, sys, time
import urllib.request

import numpy as np

repo = os.environ["REPO_DIR"]
work = os.environ["ELSERVE_WORK"]
fleet_dir = os.path.join(work, "fleet")

# The in-process reference service below shares this interpreter, so
# arm the device pool and the cross-mesh bitwise contract BEFORE any
# jax import (docs/RESHARD.md "Equality fine print").
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
os.environ["GS_FUSE"] = "1"

sys.path.insert(0, repo)
from grayscott_jl_tpu.serve.cluster import FleetKV


def member_env(rank, workers):
    env = dict(os.environ)
    env["GS_SERVE_FLEET_DIR"] = fleet_dir
    env["GS_SERVE_FLEET_RANK"] = str(rank)
    env["GS_SERVE_PORT"] = "0"
    env["GS_SERVE_WORKERS"] = str(workers)
    env["GS_SERVE_STATE_DIR"] = os.path.join(work, f"state{rank}")
    env["GS_SERVE_LEASE_TTL_S"] = "3.0"
    env["GS_SERVE_HEARTBEAT_S"] = "0.5"
    env["GS_SERVE_PACK_MAX"] = "2"
    env["GS_SERVE_PACK_WINDOW_S"] = "0.1"
    env["GS_SERVE_SUPERVISE"] = "0"
    env["GS_EVENTS"] = os.path.join(work, "events.jsonl")
    return env


def post(base, path, payload):
    req = urllib.request.Request(
        base + path, data=json.dumps(payload).encode()
    )
    with urllib.request.urlopen(req) as r:
        return json.loads(r.read())


def get(base, path):
    with urllib.request.urlopen(base + path) as r:
        return json.loads(r.read())


def spec(i):
    # Long enough (12 step rounds) that the reshape requests land
    # strictly mid-run; checkpoints arm the killed batch's resume.
    return {
        "tenant": "chaos", "model": "grayscott", "L": 16, "steps": 48,
        "plotgap": 4, "checkpoint_freq": 8, "dt": 1.0, "noise": 0.1,
        "seed": 300 + i,
        "params": {"F": 0.03 + 0.002 * i, "k": 0.062,
                   "Du": 0.2, "Dv": 0.1},
    }


procs = []
for rank, role in ((0, "frontdoor"), (1, "worker"), (2, "worker")):
    args = [sys.executable, os.path.join(repo, "scripts", "gs_serve.py")]
    if role == "worker":
        args += ["--role", "worker"]
    procs.append(subprocess.Popen(
        args, env=member_env(rank, 1 if role == "worker" else 0),
        cwd=work,
        stdout=open(os.path.join(work, f"member{rank}.log"), "w"),
        stderr=subprocess.STDOUT,
    ))

kv = FleetKV(fleet_dir)
base = None
deadline = time.time() + 120
while time.time() < deadline and base is None:
    for mid in kv.keys("members"):
        doc = kv.get(f"members/{mid}")
        if doc and doc.get("role") == "frontdoor" and doc.get("port"):
            base = f"http://{doc['host']}:{doc['port']}"
    time.sleep(0.2)
assert base is not None, "the front door never announced"

jobs = [post(base, "/v1/jobs", spec(i))["job"] for i in range(4)]

# Two packed batches, one lease per worker. batch A gets the live
# grow+shrink cycle; batch B's worker is the SIGKILL victim.
leases = {}
deadline = time.time() + 120
while time.time() < deadline and len(leases) < 2:
    for bid in kv.keys("leases"):
        lease = kv.get(f"leases/{bid}")
        mdoc = lease and kv.get(f"members/{lease['worker']}")
        if mdoc:
            leases[bid] = mdoc["pid"]
    time.sleep(0.05)
assert len(leases) == 2, f"expected two concurrent leases: {leases}"
(batch_a, pid_a), (batch_b, pid_b) = sorted(leases.items())


def steer(batch_id, scale, wait=True):
    # The exact doc ClusterScheduler.request_reshape publishes; the
    # leasing worker's between-rounds poll consumes it atomically.
    kv.put(f"reshape/{batch_id}", {
        "batch": batch_id, "req": {"scale": scale},
        "by": "chaos", "t": time.time(),
    })
    if not wait:
        return
    deadline = time.time() + 120
    while time.time() < deadline:
        if kv.get(f"reshape/{batch_id}") is None:
            return
        time.sleep(0.05)
    raise AssertionError(f"{scale} request for {batch_id} never consumed")


# Live cycle on batch A: halve the mesh, then double it back — both
# consumed while the batch is RUNNING (reshard events prove the moves
# really executed in-job).
steer(batch_a, "shrink")
steer(batch_a, "grow")

# Batch B: the reshape request lands and its worker dies on the spot —
# mid-reshape. The lease expires, the reaper deletes the orphaned doc,
# and the surviving worker adopts the checkpoint-quorum resume.
steer(batch_b, "shrink", wait=False)
time.sleep(0.1)
os.kill(pid_b, signal.SIGKILL)

deadline = time.time() + 420
records = []
while time.time() < deadline:
    records = [get(base, f"/v1/jobs/{j}") for j in jobs]
    if all(r["state"] in ("complete", "failed") for r in records):
        break
    time.sleep(0.3)
states = [r["state"] for r in records]
assert states == ["complete"] * 4, f"elastic serve job states: {states}"

for p in procs:
    if p.poll() is None:
        p.send_signal(signal.SIGTERM)
for p in procs:
    try:
        p.wait(timeout=60)
    except subprocess.TimeoutExpired:
        p.kill()

# Uninterrupted, never-reshaped reference: the same four specs through
# one in-process service with the same packing.
os.environ["GS_SERVE_STATE_DIR"] = os.path.join(work, "ref")
os.environ["GS_SERVE_PORT"] = "0"
os.environ["GS_SERVE_WORKERS"] = "1"
os.environ["GS_SERVE_PACK_MAX"] = "2"
os.environ["GS_SERVE_PACK_WINDOW_S"] = "0.2"
from grayscott_jl_tpu.serve.scheduler import resolve_serve_config
from grayscott_jl_tpu.serve.server import ServeService

svc = ServeService(resolve_serve_config()).start()
ref_base = f"http://127.0.0.1:{svc.port}"
ref_jobs = [post(ref_base, "/v1/jobs", spec(i))["job"] for i in range(4)]
deadline = time.time() + 300
while time.time() < deadline:
    ref_records = [get(ref_base, f"/v1/jobs/{j}") for j in ref_jobs]
    if all(r["state"] in ("complete", "failed") for r in ref_records):
        break
    time.sleep(0.3)
svc.close()
assert [r["state"] for r in ref_records] == ["complete"] * 4

# Store identity per job: the .vtk series is written globally and must
# stay RAW-byte identical; a .bp store that changed mesh mid-life
# frames later steps in the new blocks, so it is compared on what it
# SERVES — every step's assembled arrays, bitwise (the scenario-5
# equality fine print).
from grayscott_jl_tpu.io.bplite import BpReader

for r, ref in zip(records, ref_records):
    a, b = BpReader(r["store"]), BpReader(ref["store"])
    assert a.attributes() == b.attributes(), (r["store"], ref["store"])
    assert a.num_steps() == b.num_steps(), (r["store"], ref["store"])
    for i in range(a.num_steps()):
        for name in a.available_variables():
            x = np.asarray(a.get(name, step=i))
            y = np.asarray(b.get(name, step=i))
            assert x.dtype == y.dtype and np.array_equal(x, y), (
                r["store"], name, i)
    va = r["store"].replace(".bp", ".vtk")
    vb = ref["store"].replace(".bp", ".vtk")
    cmp = filecmp.dircmp(va, vb)
    assert not (cmp.left_only or cmp.right_only or cmp.diff_files), (
        f"{va} differs from uninterrupted {vb}")
    assert all(
        open(os.path.join(va, f), "rb").read()
        == open(os.path.join(vb, f), "rb").read()
        for f in cmp.common_files
    ), f"{va} not byte-identical to {vb}"

print(f"elastic serve chaos: batch {batch_a} grew+shrank live, "
      f"worker {pid_b} SIGKILLed mid-reshape on {batch_b}; "
      f"4/4 jobs complete, stores identical to the unmoved reference")
EOF
# The live moves must be on the merged stream with device-path
# provenance, and the whole multi-rank stream must validate.
grep -aq '"kind": "reshard"' "$WORK/elserve"/events.jsonl.rank* || {
  echo "chaos_smoke: FAIL — no reshard event from the live serve reshapes" >&2
  exit 1
}
PYTHONPATH="${REPO}${PYTHONPATH:+:${PYTHONPATH}}" python3 \
  "${REPO}/scripts/gs_report.py" --check \
  --events "$WORK/elserve/events.jsonl" || {
  echo "chaos_smoke: FAIL — gs_report.py --check rejected the elastic serve events" >&2
  exit 1
}

echo "chaos_smoke: [11/11] SDC — compute-path bitflip -> detect, attribute, quarantine..."
# Screening happens at plot/checkpoint boundaries (10/20/.../60,
# checkpoints at 20/40), so a corrupt step in [21, 29] is caught by the
# boundary-30 replay and resumed from the VERIFIED checkpoint 20, and
# the same-device repeat in [41, 49] is caught at 50 and quarantines
# the chip. Seeded like the other scenarios, printed for replay.
SDC1="$(python3 -c "import zlib; print(21 + zlib.crc32(b'sdc1:${SEED}') % 9)")"
SDC2="$(python3 -c "import zlib; print(41 + zlib.crc32(b'sdc2:${SEED}') % 9)")"
echo "chaos_smoke: seed=${SEED} -> sdc faults at steps ${SDC1} and ${SDC2} on cpu:5"
mkdir -p "$WORK/sdcref" "$WORK/sdc"
for d in sdcref sdc; do write_config "$WORK/$d"; done
# The reference is fault-free but SCREENED the same way: spot screening
# is bitwise-transparent, so like compares with like.
run "$WORK/sdcref" \
  GS_SDC_CHECK=spot \
  > "$WORK/sdcref.log" 2>&1
run "$WORK/sdc" \
  GS_SDC_CHECK=spot \
  GS_SUPERVISE=1 \
  GS_MAX_RESTARTS=5 \
  GS_RESTART_BACKOFF_S=0.05 \
  GS_EVENTS="$WORK/sdc/events.jsonl" \
  GS_FAULTS="step=${SDC1}:kind=sdc;step=${SDC2}:kind=sdc" \
  GS_FAULT_DEVICE=cpu:5 \
  > "$WORK/sdc.log" 2>&1

grep -aq '"kind": "sdc_mismatch"' "$WORK/sdc/events.jsonl" || {
  echo "chaos_smoke: FAIL — the screen never caught the injected SDC" >&2
  exit 1
}
grep -aq '"device": "cpu:5"' "$WORK/sdc/events.jsonl" || {
  echo "chaos_smoke: FAIL — no attribution to the injected device" >&2
  exit 1
}
grep -aq '"kind": "device_quarantined"' "$WORK/sdc/events.jsonl" || {
  echo "chaos_smoke: FAIL — the repeat offender was never quarantined" >&2
  exit 1
}
grep -aq 'resumed_from_checkpoint_step_20' "$WORK/sdc/events.jsonl" || {
  echo "chaos_smoke: FAIL — recovery did not resume from the verified checkpoint (20)" >&2
  exit 1
}
grep -aq 'quarantined_cpu:5' "$WORK/sdc/events.jsonl" || {
  echo "chaos_smoke: FAIL — no quarantine action on the recovery record" >&2
  exit 1
}
# Stores content-identical to the fault-free screened run: the
# post-quarantine mesh has fewer devices, so gs.bp is compared on what
# it SERVES (the scenario-5/10 fine print); the globally-written .vtk
# series must match raw bytes.
PYTHONPATH="${REPO}${PYTHONPATH:+:${PYTHONPATH}}" \
  python3 - "$WORK/sdcref" "$WORK/sdc" <<'EOF'
import filecmp
import os
import sys

import numpy as np

from grayscott_jl_tpu.io.bplite import BpReader

ref, chaos = sys.argv[1], sys.argv[2]
a = BpReader(os.path.join(ref, "gs.bp"))
b = BpReader(os.path.join(chaos, "gs.bp"))
assert a.attributes() == b.attributes()
assert a.num_steps() == b.num_steps(), (a.num_steps(), b.num_steps())
for i in range(a.num_steps()):
    for name in a.available_variables():
        x = np.asarray(a.get(name, step=i))
        y = np.asarray(b.get(name, step=i))
        assert x.dtype == y.dtype and np.array_equal(x, y), (name, i)
va, vb = os.path.join(ref, "gs.vtk"), os.path.join(chaos, "gs.vtk")
cmp = filecmp.dircmp(va, vb)
assert not (cmp.left_only or cmp.right_only or cmp.diff_files), vars(cmp)
assert all(
    open(os.path.join(va, f), "rb").read()
    == open(os.path.join(vb, f), "rb").read()
    for f in cmp.common_files
), "vtk series not byte-identical"
print("sdc chaos: detected, attributed, quarantined; stores identical")
EOF
PYTHONPATH="${REPO}${PYTHONPATH:+:${PYTHONPATH}}" python3 \
  "${REPO}/scripts/gs_report.py" --check \
  --events "$WORK/sdc/events.jsonl" || {
  echo "chaos_smoke: FAIL — gs_report.py --check rejected the SDC events" >&2
  exit 1
}

echo "chaos_smoke: PASS — all eleven scenarios recovered byte-identical" \
     "(journals: sup=$(wc -l < "$WORK/sup/gs.bp.faults.jsonl")" \
     "hang=$(wc -l < "$WORK/hang/gs.bp.faults.jsonl")" \
     "term=$(wc -l < "$WORK/term/gs.bp.faults.jsonl")" \
     "ens=$(wc -l < "$WORK/enssup/gs.bp.faults.jsonl")" \
     "elastic=$(wc -l < "$WORK/elastic/gs.bp.faults.jsonl") events)"
