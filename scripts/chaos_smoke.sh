#!/usr/bin/env bash
# Chaos smoke test: kill a small supervised run with an injected
# preemption at a pseudo-random step and assert the recovered run's
# stores are byte-identical to an uninterrupted run's.
#
# The preemption step is derived deterministically from a seed (crc32,
# printed below), so a failing run is replayable bit-for-bit:
#
#   ./scripts/chaos_smoke.sh [seed]     # default seed 0, or $CHAOS_SEED
#
# The fast fixed-step variant of this scenario runs in tier-1 as
# tests/functional/test_supervisor.py; this script is the
# operator-facing knob-twister (vary the seed, watch the journal).
# See docs/RESILIENCE.md for the failure taxonomy and knobs.

set -euo pipefail

SEED="${1:-${CHAOS_SEED:-0}}"
REPO="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
WORK="$(mktemp -d)"
trap 'rm -rf "$WORK"' EXIT

STEPS=60
# Pseudo-random preemption step in [5, 54] — strictly mid-run, printed
# so a failure is reproducible by re-running with the same seed.
PREEMPT="$(python3 -c "import zlib; print(5 + zlib.crc32(b'chaos:${SEED}') % ($STEPS - 10))")"
echo "chaos_smoke: seed=${SEED} -> injected preemption at step ${PREEMPT}"

write_config() {
  cat > "$1/config.toml" <<EOF
L = 32
Du = 0.2
Dv = 0.1
F = 0.02
k = 0.048
dt = 1.0
plotgap = 10
steps = ${STEPS}
noise = 0.1
output = "gs.bp"
checkpoint = true
checkpoint_freq = 20
checkpoint_output = "ckpt.bp"
precision = "Float32"
backend = "CPU"
kernel_language = "Plain"
verbose = true
EOF
}

run() {
  local dir="$1"; shift
  (
    cd "$dir"
    env "$@" \
      JAX_PLATFORMS=cpu \
      XLA_FLAGS="--xla_force_host_platform_device_count=8" \
      PYTHONPATH="${REPO}${PYTHONPATH:+:${PYTHONPATH}}" \
      python3 "${REPO}/gray-scott.py" config.toml
  )
}

mkdir -p "$WORK/full" "$WORK/sup"
write_config "$WORK/full"
write_config "$WORK/sup"

echo "chaos_smoke: uninterrupted reference run..."
run "$WORK/full" > "$WORK/full.log" 2>&1

echo "chaos_smoke: supervised run with injected preemption..."
run "$WORK/sup" \
  GS_SUPERVISE=1 \
  GS_MAX_RESTARTS=5 \
  GS_RESTART_BACKOFF_S=0.05 \
  GS_FAULTS="step=${PREEMPT}:kind=preempt" \
  > "$WORK/sup.log" 2>&1

grep -a "supervisor:" "$WORK/sup.log" || {
  echo "chaos_smoke: FAIL — the supervisor never recovered anything" >&2
  exit 1
}

for store in gs.bp gs.vtk ckpt.bp; do
  if ! diff -r "$WORK/full/$store" "$WORK/sup/$store" > /dev/null; then
    echo "chaos_smoke: FAIL — $store differs from the uninterrupted run" >&2
    diff -rq "$WORK/full/$store" "$WORK/sup/$store" >&2 || true
    exit 1
  fi
done

echo "chaos_smoke: PASS — recovered run is byte-identical" \
     "(journal: $(wc -l < "$WORK/sup/gs.bp.faults.jsonl") events)"
