#!/usr/bin/env python3
"""Human run report from the observability artifacts.

Renders the three obs outputs (docs/OBSERVABILITY.md) — the
``GS_TPU_STATS`` summary JSON, the ``GS_TRACE`` Chrome trace, and the
``GS_EVENTS`` unified stream — into one operator-facing story: where
the wall time went, the slowest step rounds, how much I/O and comm was
exposed vs hidden, the step-latency percentiles, and the fault /
restart timeline with per-attempt wall-time attribution.

    python scripts/gs_report.py --stats stats.json --trace trace.json \
        --events events.jsonl [--top 5]

    # CI validation mode: schema-check the artifacts, render nothing
    python scripts/gs_report.py --check --trace trace.json \
        --events events.jsonl

Runs without JAX (stdlib + the jax-free ``grayscott_jl_tpu.obs``
helpers only) so it works on a laptop holding artifacts scp'd off a
pod. Exit code: 0 on success, 1 when ``--check`` finds a problem or a
requested artifact is unreadable.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from grayscott_jl_tpu.obs.events import parse_events  # noqa: E402
from grayscott_jl_tpu.obs.trace import validate_trace  # noqa: E402


def _fmt_s(v) -> str:
    return f"{v:.3f}s" if isinstance(v, (int, float)) else "-"


def check(trace_path, events_path, stats_path) -> int:
    """Schema validation (the chaos_smoke / CI entry): returns the
    process exit code."""
    problems = []
    if trace_path:
        try:
            with open(trace_path, encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            problems.append(f"trace {trace_path}: unreadable ({e})")
        else:
            for p in validate_trace(doc):
                problems.append(f"trace {trace_path}: {p}")
            n = sum(1 for e in doc.get("traceEvents", [])
                    if isinstance(e, dict) and e.get("ph") == "X")
            if n == 0:
                problems.append(f"trace {trace_path}: no spans")
    if events_path:
        try:
            events = parse_events(events_path)
        except OSError as e:
            problems.append(f"events {events_path}: unreadable ({e})")
        else:
            if not events:
                problems.append(f"events {events_path}: no events")
            for i, e in enumerate(events):
                missing = [k for k in ("ts", "kind") if k not in e]
                if missing:
                    problems.append(
                        f"events {events_path}: record {i} missing "
                        f"{missing}"
                    )
    if stats_path:
        try:
            with open(stats_path, encoding="utf-8") as f:
                stats = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            problems.append(f"stats {stats_path}: unreadable ({e})")
        else:
            comm = stats.get("comm") if isinstance(stats, dict) else None
            if isinstance(comm, dict):
                # The s-step visibility fields (docs/TEMPORAL.md) are
                # part of the comm schema: a stats writer that drops
                # them silently hides the exchange cadence the
                # halo_depth knob exists to change.
                missing = [k for k in ("halo_depth",
                                       "exchanges_per_step",
                                       "halo_bytes_per_step")
                           if k not in comm]
                if missing:
                    problems.append(
                        f"stats {stats_path}: comm section missing "
                        f"{missing}"
                    )
    for p in problems:
        print(f"gs_report: FAIL — {p}", file=sys.stderr)
    if not problems:
        print("gs_report: OK — artifacts validate")
    return 1 if problems else 0


def report_stats(stats: dict) -> None:
    cfg = stats.get("config", {})
    print("== run ==")
    print(f"  model={cfg.get('model')} L={stats.get('L')} "
          f"mesh={cfg.get('mesh_dims')} kernel="
          f"{cfg.get('kernel_language')} devices="
          f"{cfg.get('n_devices')} attempt={cfg.get('attempt', 0)}")
    print(f"  steps={stats.get('steps')} wall={_fmt_s(stats.get('wall_s'))} "
          f"cell-updates/s={stats.get('cell_updates_per_s')}")
    phases = stats.get("phases_s") or {}
    total = sum(phases.values()) or 1.0
    print("== phases ==")
    for name, v in sorted(phases.items(), key=lambda kv: -kv[1]):
        print(f"  {name:<16} {v:10.3f}s  {100 * v / total:5.1f}%")
    io = stats.get("io")
    if io:
        hidden = sum((io.get("hidden_s") or {}).values())
        exposed = sum((io.get("exposed_s") or {}).values())
        busy = hidden + exposed
        frac = exposed / busy if busy > 0 else 0.0
        print("== i/o overlap ==")
        print(f"  busy={busy:.3f}s hidden={hidden:.3f}s "
              f"exposed={exposed:.3f}s ({100 * frac:.1f}% exposed), "
              f"queue hwm={io.get('queue_depth_hwm')}")
    comm = stats.get("comm")
    if comm and comm.get("comm_us_per_step"):
        print("== comm (model projection) ==")
        print(f"  {comm.get('comm_us_per_step')}us/step, hidden="
              f"{comm.get('hidden_us')}us exposed="
              f"{comm.get('exposed_us')}us "
              f"(overlap={comm.get('overlap')})")
        ex = comm.get("exchanges_per_step")
        if ex is not None:
            per = round(1.0 / ex, 2) if ex else float("inf")
            print(f"  halo_depth={comm.get('halo_depth')}: one exchange "
                  f"per {per} steps, "
                  f"{comm.get('halo_bytes_per_step')} halo B/step")
    metrics = stats.get("metrics")
    if metrics:
        for h in metrics.get("histograms", []):
            if h.get("name") == "step_latency_us":
                print("== step latency (per fused round) ==")
                print(f"  p50={h.get('p50')}us p95={h.get('p95')}us "
                      f"p99={h.get('p99')}us mean={h.get('mean')}us "
                      f"over {h.get('count')} rounds")


def report_attempts(events) -> None:
    """Per-attempt wall-time attribution from ``attempt_phases``
    journal events (stats ``faults`` section or the event stream)."""
    rows = [e for e in events if e.get("kind") == "attempt_phases"
            or e.get("event") == "attempt_phases"]
    if not rows:
        return
    print("== attempts ==")
    for e in rows:
        attrs = e.get("attrs", e)
        phases = attrs.get("phases_s") or {}
        print(f"  attempt {attrs.get('attempt')}: "
              f"ended as {attrs.get('fault', attrs.get('kind'))} after "
              f"{attrs.get('steps')} steps, "
              f"compute={_fmt_s(phases.get('compute'))}")


def report_timeline(events, top: int) -> None:
    """The fault/recovery story, oldest first, with relative times."""
    interesting = [e for e in events if e.get("kind") not in
                   ("output", "checkpoint")]
    if not interesting:
        return
    t0 = interesting[0].get("ts") or 0
    print("== timeline ==")
    for e in interesting:
        attrs = e.get("attrs") or {}
        extra = ""
        if attrs.get("fault"):
            extra += f" fault={attrs['fault']}"
        if attrs.get("action"):
            extra += f" action={attrs['action']}"
        if attrs.get("error"):
            extra += f" error={attrs['error']}"
        if attrs.get("cache"):
            extra += f" cache={attrs['cache']}"
        step = e.get("step")
        print(f"  +{(e.get('ts') or t0) - t0:8.3f}s  "
              f"{e.get('kind', '?'):<20} "
              f"{'step ' + str(step) if step is not None else '':<10}"
              f"{extra}")


def report_slow_rounds(doc: dict, top: int) -> None:
    spans = [e for e in doc.get("traceEvents", [])
             if isinstance(e, dict) and e.get("ph") == "X"
             and e.get("name") in ("step_round", "compute", "compile")]
    if not spans:
        return
    spans.sort(key=lambda e: -e["dur"])
    print(f"== slowest rounds (top {top}) ==")
    for e in spans[:top]:
        step = (e.get("args") or {}).get("step")
        print(f"  {e['name']:<12} step={step!s:<8} "
              f"{e['dur'] / 1e3:10.3f}ms at t+{e['ts'] / 1e6:.3f}s")


def main() -> int:
    ap = argparse.ArgumentParser(
        description="render gray-scott observability artifacts"
    )
    ap.add_argument("--stats", help="GS_TPU_STATS summary JSON")
    ap.add_argument("--trace", help="GS_TRACE Chrome trace JSON")
    ap.add_argument("--events", help="GS_EVENTS unified stream JSONL")
    ap.add_argument("--check", action="store_true",
                    help="validate schemas only; no report")
    ap.add_argument("--top", type=int, default=5,
                    help="slowest rounds to list (default 5)")
    args = ap.parse_args()
    if not (args.stats or args.trace or args.events):
        ap.error("need at least one of --stats / --trace / --events")
    if args.check:
        return check(args.trace, args.events, args.stats)

    stats = None
    if args.stats:
        with open(args.stats, encoding="utf-8") as f:
            stats = json.load(f)
        report_stats(stats)
    if args.trace:
        with open(args.trace, encoding="utf-8") as f:
            doc = json.load(f)
        problems = validate_trace(doc)
        if problems:
            print(f"gs_report: warning — trace has "
                  f"{len(problems)} schema problem(s)", file=sys.stderr)
        report_slow_rounds(doc, args.top)
    events = []
    if args.events:
        events = parse_events(args.events)
    elif stats and stats.get("faults"):
        events = stats["faults"]
    if events:
        report_attempts(events)
        report_timeline(events, args.top)
    return 0


if __name__ == "__main__":
    sys.exit(main())
