#!/usr/bin/env python3
"""Human run report from the observability artifacts.

Renders the three obs outputs (docs/OBSERVABILITY.md) — the
``GS_TPU_STATS`` summary JSON, the ``GS_TRACE`` Chrome trace, and the
``GS_EVENTS`` unified stream — into one operator-facing story: where
the wall time went, the slowest step rounds, how much I/O and comm was
exposed vs hidden, the step-latency percentiles, and the fault /
restart timeline with per-attempt wall-time attribution.

    python scripts/gs_report.py --stats stats.json --trace trace.json \
        --events events.jsonl [--top 5]

    # CI validation mode: schema-check the artifacts, render nothing
    python scripts/gs_report.py --check --trace trace.json \
        --events events.jsonl

Runs without JAX (stdlib + the jax-free ``grayscott_jl_tpu.obs``
helpers only) so it works on a laptop holding artifacts scp'd off a
pod. Exit code: 0 on success, 1 when ``--check`` finds a problem or a
requested artifact is unreadable.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from grayscott_jl_tpu.obs.events import (  # noqa: E402
    parse_events_multi,
    rank_files,
)
from grayscott_jl_tpu.obs.trace import validate_trace  # noqa: E402


def _fmt_s(v) -> str:
    return f"{v:.3f}s" if isinstance(v, (int, float)) else "-"


#: The full GS_EVENTS kind registry: every kind a producer in the tree
#: can emit, mapped to the attrs it must carry (docs/OBSERVABILITY.md).
#: Kept in sync with the producers by the ``event-schema`` gslint pass
#: (docs/ANALYSIS.md) — an emit of a kind missing here, or an entry
#: here nothing emits, fails ``scripts/gslint.py``.  Journal-mirrored
#: kinds (``FaultJournal.record``) carry their failure-taxonomy
#: ``kind`` as the ``fault`` attr.
EVENT_KIND_SCHEMA = {
    # driver lifecycle
    "run_start": ("model", "L", "steps", "kernel", "mesh"),
    "output": ("output_step",),
    "checkpoint": (),
    "run_complete": ("wall_s", "steps", "attempt"),
    "run_error": ("error", "attempt"),
    "shutdown_requested": ("signum",),
    # tuning / observability producers
    "autotune": ("mode", "source", "kernel"),
    "numerics": ("fields",),
    "drift": ("tripped", "limit", "policy"),
    "executable": ("name", "compile_s"),
    # resilience (journal-mirrored)
    "injected": ("fault", "planned_step"),
    "health": ("fault", "policy", "action"),
    "recovery": ("fault", "attempt", "action"),
    "gave_up": ("fault", "attempt", "error"),
    "attempt_phases": ("attempt", "phases_s", "steps"),
    "rendezvous": ("round", "attempt", "procs"),
    "mesh_agreement": ("round", "devices", "procs"),
    "graceful_shutdown": ("signal",),
    "hang": ("fault", "deadline_s", "threads"),
    "hang_exit": ("fault", "exit_code"),
    # elastic resharding: every move — host checkpoint restore or
    # live device reshape — records its path tier (ckpt / collective /
    # put / host), true-domain bytes moved, and wall time, so reshard
    # cost is first-class provenance (docs/RESHARD.md).
    "reshard": ("members", "path", "bytes", "wall_s"),
    # the serve elastic policy's grow/shrink decisions
    # (serve/elastic.py, docs/SERVICE.md "Elastic capacity")
    "elastic": ("action", "batch", "depth", "utilization"),
    # data integrity (resilience/integrity.py, docs/RESILIENCE.md):
    # detected silent corruption (CRC / device-checksum mismatch,
    # damaged writer metadata), a restore failing over to a healthy
    # checkpoint replica, and the boundary scrubber's audit summary.
    # The injected chaos kinds (`bitflip`, `ckpt_corrupt`) ride the
    # `injected` record like every other fault, in its `fault` attr.
    "corruption": ("detail",),
    "replica_failover": ("path", "detail"),
    "scrub": ("path", "steps_audited", "corrupt"),
    # simulation-as-a-service job lifecycle (serve/, docs/SERVICE.md);
    # every record carries the tenant so the per-tenant timeline below
    # can attribute multi-tenant traffic from one stream.
    "job_submitted": ("job", "tenant", "priority", "model", "L",
                      "steps"),
    "job_packed": ("job", "tenant", "batch", "slot", "members"),
    "job_requeued": ("job", "tenant", "batch", "fault"),
    "job_complete": ("job", "tenant", "status"),
    "job_rejected": ("job", "tenant", "reason"),
    # distributed serve fleet + result cache (serve/cluster.py,
    # serve/cache.py; docs/SERVICE.md "the distributed fleet"):
    # membership joins/losses, a dead worker's batch failing over to
    # the fleet, and the content-addressed cache's hit/miss/publish
    # provenance (the digest names the physics; byte-identical replay
    # is the contract).
    # compute-path SDC screening (resilience/sdc.py,
    # docs/RESILIENCE.md "Silent data corruption"): every redundant-
    # compute check (ok or not), a mismatch's device/member
    # attribution, the quarantine verdict, and a serve member marking
    # its own inventory suspect. The injected chaos kind (`sdc`) rides
    # the `injected` record like every other fault.
    "sdc_check": ("mode", "replayed_steps", "status"),
    "sdc_mismatch": ("mode", "device", "replayed_steps",
                     "verified_step"),
    "device_quarantined": ("device", "reason"),
    "worker_degraded": ("reason",),
    "worker_join": ("worker", "role"),
    "worker_lost": ("worker",),
    "job_failover": ("job", "tenant", "batch", "worker"),
    "cache_hit": ("digest", "job", "tenant"),
    "cache_miss": ("digest", "job", "tenant"),
    "cache_publish": ("digest", "job", "store"),
}


def _check_event(path, i, e, problems) -> None:
    missing = [k for k in ("ts", "kind") if k not in e]
    if missing:
        problems.append(
            f"events {path}: record {i} missing {missing}"
        )
        return
    if e["kind"] not in EVENT_KIND_SCHEMA:
        problems.append(
            f"events {path}: record {i} has unknown kind "
            f"{e['kind']!r} (not in EVENT_KIND_SCHEMA)"
        )
        return
    required = EVENT_KIND_SCHEMA[e["kind"]]
    if required:
        attrs = e.get("attrs") or {}
        missing = [k for k in required if k not in attrs]
        if missing:
            problems.append(
                f"events {path}: {e['kind']} record {i} missing "
                f"attrs {missing}"
            )
        if e.get("kind") == "numerics" and "fields" not in missing:
            for fname, stats in (attrs["fields"] or {}).items():
                bad = [s for s in ("min", "max", "mean", "l2",
                                   "nonfinite")
                       if not isinstance(stats.get(s), (int, float))]
                if bad:
                    problems.append(
                        f"events {path}: numerics record {i} field "
                        f"{fname!r} missing stats {bad}"
                    )


def _check_halo_depth_gate(stats_path, gate, problems) -> None:
    """Validate a ``halo_depth_gate`` provenance record
    (docs/TEMPORAL.md): a degraded s-step request must say what was
    asked, what ran, and WHY.  Two generations exist: the legacy
    blanket-degrade record (requested/applied/reason only) and the
    geometry-infeasible record (``kind`` + the VMEM ledger numbers in
    ``geometry``) — a ``kind`` outside that registry, or a ledger
    record missing its numbers, is a producer bug."""
    if gate is None:
        return
    if not isinstance(gate, dict):
        problems.append(
            f"stats {stats_path}: halo_depth_gate must be a dict, "
            f"got {type(gate).__name__}"
        )
        return
    for k in ("requested", "applied"):
        if not isinstance(gate.get(k), int):
            problems.append(
                f"stats {stats_path}: halo_depth_gate missing "
                f"integer {k!r}"
            )
    reason = gate.get("reason")
    if not (isinstance(reason, str) and reason.strip()):
        problems.append(
            f"stats {stats_path}: halo_depth_gate must carry a "
            f"nonempty reason string"
        )
    if "kind" not in gate:
        return  # legacy blanket-degrade record (pre-v8): accepted
    if gate["kind"] != "geometry-infeasible":
        problems.append(
            f"stats {stats_path}: halo_depth_gate kind must be "
            f"'geometry-infeasible', got {gate['kind']!r}"
        )
        return
    geo = gate.get("geometry")
    if not isinstance(geo, dict):
        problems.append(
            f"stats {stats_path}: geometry-infeasible "
            f"halo_depth_gate must carry a geometry ledger dict"
        )
        return
    for k in ("fuse_base", "requested_depth", "feasible_depth",
              "vmem_budget_bytes", "itemsize", "n_fields"):
        if not isinstance(geo.get(k), int):
            problems.append(
                f"stats {stats_path}: halo_depth_gate geometry "
                f"missing integer {k!r}"
            )
    shape = geo.get("local_shape")
    if not (isinstance(shape, list) and len(shape) == 3
            and all(isinstance(v, int) for v in shape)):
        problems.append(
            f"stats {stats_path}: halo_depth_gate geometry "
            f"local_shape must be a 3-int list, got {shape!r}"
        )


def check(trace_path, events_path, stats_path,
          metrics_path=None) -> int:
    """Schema validation (the chaos_smoke / CI entry): returns the
    process exit code. Multi-process runs are validated across every
    ``.rank<N>`` sibling of the named events/metrics path."""
    problems = []
    if trace_path:
        try:
            with open(trace_path, encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            problems.append(f"trace {trace_path}: unreadable ({e})")
        else:
            for p in validate_trace(doc):
                problems.append(f"trace {trace_path}: {p}")
            n = sum(1 for e in doc.get("traceEvents", [])
                    if isinstance(e, dict) and e.get("ph") == "X")
            if n == 0:
                problems.append(f"trace {trace_path}: no spans")
    if events_path:
        try:
            events = parse_events_multi(events_path)
        except OSError as e:
            problems.append(f"events {events_path}: unreadable ({e})")
        else:
            if not events:
                problems.append(f"events {events_path}: no events")
            for i, e in enumerate(events):
                _check_event(events_path, i, e, problems)
    if metrics_path:
        files = rank_files(metrics_path)
        if not files:
            problems.append(f"metrics {metrics_path}: no such file")
        for p in files:
            try:
                records = _read_metrics(p)
            except (OSError, json.JSONDecodeError) as e:
                problems.append(f"metrics {p}: unreadable ({e})")
                continue
            if not records:
                problems.append(f"metrics {p}: no records")
            for i, rec in enumerate(records):
                missing = [k for k in ("ts", "proc", "counters",
                                       "gauges", "histograms")
                           if k not in rec]
                if missing:
                    problems.append(
                        f"metrics {p}: record {i} missing {missing}"
                    )
    if stats_path:
        try:
            with open(stats_path, encoding="utf-8") as f:
                stats = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            problems.append(f"stats {stats_path}: unreadable ({e})")
        else:
            cfg = (stats.get("config")
                   if isinstance(stats, dict) else None)
            if isinstance(cfg, dict):
                sel = cfg.get("kernel_selection")
                if (cfg.get("kernel_language") == "pallas"
                        and isinstance(sel, dict)):
                    # Generated-kernel provenance (docs/KERNELGEN.md):
                    # a resolved Pallas pick is a generator product,
                    # and the artifact must say which generator
                    # contract built it — hand-written-era records
                    # carry neither attr and predate this check.
                    if sel.get("generated") is not True:
                        problems.append(
                            f"stats {stats_path}: kernel_selection of "
                            f"a Pallas run must record generated=true"
                        )
                    if not isinstance(sel.get("generator_version"),
                                      int):
                        problems.append(
                            f"stats {stats_path}: kernel_selection of "
                            f"a Pallas run must record an integer "
                            f"generator_version"
                        )
                if isinstance(sel, dict):
                    at = sel.get("autotune")
                    if isinstance(at, dict) and "cache_schema" in at:
                        # v8 tuning provenance (docs/TUNING.md): the
                        # schema the decision was keyed under rides in
                        # the artifact; pre-v8 records carry no field
                        # and predate this check.
                        if not isinstance(at["cache_schema"], int):
                            problems.append(
                                f"stats {stats_path}: autotune "
                                f"provenance cache_schema must be an "
                                f"integer, got "
                                f"{at['cache_schema']!r}"
                            )
                    _check_halo_depth_gate(
                        stats_path, sel.get("halo_depth_gate"),
                        problems,
                    )
            rs = (cfg.get("reshard")
                  if isinstance(cfg, dict) else None)
            if isinstance(rs, dict) and rs.get("changed"):
                # Reshard provenance (docs/RESHARD.md): a run that
                # moved must say HOW — which path tier carried it,
                # how many bytes, how long.
                if rs.get("path") not in ("ckpt", "collective",
                                          "put", "host"):
                    problems.append(
                        f"stats {stats_path}: reshard record must "
                        f"carry a path tier (ckpt/collective/put/"
                        f"host), got {rs.get('path')!r}"
                    )
                for k in ("bytes", "wall_s"):
                    if not isinstance(rs.get(k), (int, float)):
                        problems.append(
                            f"stats {stats_path}: reshard record "
                            f"missing numeric {k!r}"
                        )
            comm = stats.get("comm") if isinstance(stats, dict) else None
            if isinstance(comm, dict):
                # The s-step visibility fields (docs/TEMPORAL.md) are
                # part of the comm schema: a stats writer that drops
                # them silently hides the exchange cadence the
                # halo_depth knob exists to change.
                missing = [k for k in ("halo_depth",
                                       "exchanges_per_step",
                                       "halo_bytes_per_step")
                           if k not in comm]
                if missing:
                    problems.append(
                        f"stats {stats_path}: comm section missing "
                        f"{missing}"
                    )
    for p in problems:
        print(f"gs_report: FAIL — {p}", file=sys.stderr)
    if not problems:
        print("gs_report: OK — artifacts validate")
    return 1 if problems else 0


def _read_metrics(path: str) -> list:
    """Interval snapshot records of one metrics JSONL file (torn tail
    lines skipped, like the event stream)."""
    out = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict):
                out.append(rec)
    return out


def report_stats(stats: dict) -> None:
    cfg = stats.get("config", {})
    print("== run ==")
    print(f"  model={cfg.get('model')} L={stats.get('L')} "
          f"mesh={cfg.get('mesh_dims')} kernel="
          f"{cfg.get('kernel_language')} devices="
          f"{cfg.get('n_devices')} attempt={cfg.get('attempt', 0)}")
    print(f"  steps={stats.get('steps')} wall={_fmt_s(stats.get('wall_s'))} "
          f"cell-updates/s={stats.get('cell_updates_per_s')}")
    phases = stats.get("phases_s") or {}
    total = sum(phases.values()) or 1.0
    print("== phases ==")
    for name, v in sorted(phases.items(), key=lambda kv: -kv[1]):
        print(f"  {name:<16} {v:10.3f}s  {100 * v / total:5.1f}%")
    io = stats.get("io")
    if io:
        hidden = sum((io.get("hidden_s") or {}).values())
        exposed = sum((io.get("exposed_s") or {}).values())
        busy = hidden + exposed
        frac = exposed / busy if busy > 0 else 0.0
        print("== i/o overlap ==")
        print(f"  busy={busy:.3f}s hidden={hidden:.3f}s "
              f"exposed={exposed:.3f}s ({100 * frac:.1f}% exposed), "
              f"queue hwm={io.get('queue_depth_hwm')}")
    comm = stats.get("comm")
    if comm and comm.get("comm_us_per_step"):
        print("== comm (model projection) ==")
        print(f"  {comm.get('comm_us_per_step')}us/step, hidden="
              f"{comm.get('hidden_us')}us exposed="
              f"{comm.get('exposed_us')}us "
              f"(overlap={comm.get('overlap')})")
        ex = comm.get("exchanges_per_step")
        if ex is not None:
            per = round(1.0 / ex, 2) if ex else float("inf")
            print(f"  halo_depth={comm.get('halo_depth')}: one exchange "
                  f"per {per} steps, "
                  f"{comm.get('halo_bytes_per_step')} halo B/step")
    report_reshard(cfg.get("reshard"))
    metrics = stats.get("metrics")
    if metrics:
        for h in metrics.get("histograms", []):
            if h.get("name") == "step_latency_us":
                print("== step latency (per fused round) ==")
                print(f"  p50={h.get('p50')}us p95={h.get('p95')}us "
                      f"p99={h.get('p99')}us mean={h.get('mean')}us "
                      f"over {h.get('count')} rounds")
    report_numerics(stats.get("numerics"))
    report_executables(stats.get("executables"))


def report_reshard(rs) -> None:
    """The reshard provenance section: which path tier moved the run
    (host checkpoint restore vs the live device tiers), between which
    layouts, how many bytes, how fast (docs/RESHARD.md)."""
    if not isinstance(rs, dict) or not rs.get("changed"):
        return
    old = rs.get("old") or {}
    new = rs.get("new") or {}
    print(f"== reshard (path={rs.get('path')}) ==")
    print(f"  mesh {old.get('mesh_dims')} -> {new.get('mesh_dims')}, "
          f"procs {old.get('process_count')} -> "
          f"{new.get('process_count')}, "
          f"{rs.get('n_shards')} target shard(s)")
    by = rs.get("bytes")
    wall = rs.get("wall_s")
    if isinstance(by, (int, float)) and isinstance(wall, (int, float)):
        rate = by / wall / 1e6 if wall else float("inf")
        print(f"  moved {by} B in {_fmt_s(wall)} ({rate:.1f} MB/s)")
    members = rs.get("members")
    if members:
        print(f"  members: restored={members.get('restored')} "
              f"grown={members.get('grown')} "
              f"-> n={members.get('new_n')}")


def report_numerics(num) -> None:
    """The in-graph numerics section: last per-field statistics plus
    each statistic's worst windowed drift (docs/OBSERVABILITY.md)."""
    if not num:
        return
    print(f"== numerics (mode={num.get('mode')}, "
          f"{num.get('probes')} probes, window={num.get('window')}, "
          f"drift trips={num.get('drift_trips')}) ==")
    last = (num.get("last") or {}).get("fields") or {}
    drift = num.get("max_drift") or {}
    for field, s in last.items():
        print(f"  {field:<6} min={s.get('min'):.6g} "
              f"max={s.get('max'):.6g} mean={s.get('mean'):.6g} "
              f"l2={s.get('l2'):.6g} nonfinite={s.get('nonfinite')}")
        worst = {k.split(".", 1)[1]: v for k, v in drift.items()
                 if k.startswith(field + ".")}
        if worst:
            print("         max drift: " + " ".join(
                f"{k}={v:+.3f}" for k, v in worst.items()
            ))


def report_executables(ex) -> None:
    """The executable-analytics table: per-compile cost / memory /
    collective counts, cache outcome, and the model-vs-measured
    residual (docs/OBSERVABILITY.md)."""
    if not ex:
        return
    print(f"== executables ({ex.get('compiles')} compiles, "
          f"{_fmt_s(ex.get('compile_s_total'))} compiling, cache "
          f"{ex.get('compile_cache_hits')} hit / "
          f"{ex.get('compile_cache_misses')} miss) ==")
    for r in ex.get("records") or []:
        cost = r.get("cost") or {}
        mem = r.get("memory") or {}
        coll = r.get("collectives") or {}
        coll_s = (", ".join(f"{k}x{v}" for k, v in sorted(coll.items()))
                  or "none")
        print(f"  {r.get('name', '?'):<14} "
              f"compile={_fmt_s(r.get('compile_s'))} "
              f"flops={cost.get('flops', '-')} "
              f"bytes={cost.get('bytes_accessed', '-')} "
              f"peakB={mem.get('peak_bytes_estimate', '-')} "
              f"collectives={coll_s} "
              f"cache={r.get('cache', '-')}")
    proj = ex.get("model_projected_step_us")
    p50 = ex.get("observed_p50_us")
    res = ex.get("model_vs_measured_residual_us")
    if proj is not None or p50 is not None:
        print(f"  model projected {proj}us/step vs observed p50 "
              f"{round(p50, 1) if isinstance(p50, (int, float)) else '-'}"
              f"us -> residual {res}us")


def report_metrics_files(path: str) -> None:
    """Per-process metrics summary from (rank-merged) interval JSONL
    files: the final snapshot's headline counters and the step-latency
    percentiles, attributed per proc."""
    files = rank_files(path)
    if not files:
        return
    print(f"== metrics ({len(files)} file(s)) ==")
    for p in files:
        records = _read_metrics(p)
        if not records:
            continue
        last = records[-1]
        counters = {c.get("name"): c.get("value")
                    for c in last.get("counters", [])}
        line = (f"  proc {last.get('proc')}: "
                f"{len(records)} snapshot(s), steps="
                f"{counters.get('steps')} rounds="
                f"{counters.get('step_rounds')}")
        for h in last.get("histograms", []):
            if h.get("name") == "step_latency_us":
                line += (f", step p50={h.get('p50')}us "
                         f"p99={h.get('p99')}us")
        print(line)


def report_attempts(events) -> None:
    """Per-attempt wall-time attribution from ``attempt_phases``
    journal events (stats ``faults`` section or the event stream)."""
    rows = [e for e in events if e.get("kind") == "attempt_phases"
            or e.get("event") == "attempt_phases"]
    if not rows:
        return
    print("== attempts ==")
    for e in rows:
        attrs = e.get("attrs", e)
        phases = attrs.get("phases_s") or {}
        print(f"  attempt {attrs.get('attempt')}: "
              f"ended as {attrs.get('fault', attrs.get('kind'))} after "
              f"{attrs.get('steps')} steps, "
              f"compute={_fmt_s(phases.get('compute'))}")


def report_tenants(events) -> None:
    """The serve-side story (docs/SERVICE.md): per-tenant job
    timelines distilled from the ``job_*`` lifecycle kinds — submit ->
    packed (batch/slot) -> requeues -> terminal state, with the
    queue-wait and end-to-end latencies that make quota and SLO
    conversations concrete."""
    job_events = [e for e in events
                  if str(e.get("kind", "")).startswith("job_")]
    if not job_events:
        return
    tenants: dict = {}
    for e in job_events:
        attrs = e.get("attrs") or {}
        jid = attrs.get("job", "?")
        tenant = attrs.get("tenant", "?")
        job = tenants.setdefault(tenant, {}).setdefault(jid, {
            "requeues": 0, "status": None, "batch": None,
        })
        kind, ts = e.get("kind"), e.get("ts")
        if kind == "job_submitted":
            job["submitted"] = ts
            job["model"] = attrs.get("model")
            job["L"] = attrs.get("L")
            job["priority"] = attrs.get("priority")
        elif kind == "job_packed":
            job.setdefault("packed", ts)
            job["batch"] = attrs.get("batch")
            job["slot"] = attrs.get("slot")
        elif kind == "job_requeued":
            job["requeues"] += 1
        elif kind == "job_rejected":
            job["status"] = f"rejected({attrs.get('reason')})"
            job["finished"] = ts
        elif kind == "job_complete":
            job["status"] = attrs.get("status")
            job["finished"] = ts
    print("== tenants ==")
    for tenant in sorted(tenants):
        jobs = tenants[tenant]
        done = sum(1 for j in jobs.values()
                   if j.get("status") == "complete")
        print(f"  {tenant}: {len(jobs)} job(s), {done} complete")
        for jid in sorted(jobs):
            j = jobs[jid]
            sub, packed = j.get("submitted"), j.get("packed")
            fin = j.get("finished")
            wait = (f"wait={packed - sub:.3f}s"
                    if packed is not None and sub is not None else "")
            total = (f"total={fin - sub:.3f}s"
                     if fin is not None and sub is not None else "")
            req = (f" requeues={j['requeues']}" if j["requeues"]
                   else "")
            batch = (f" batch={j['batch']}/s{j.get('slot')}"
                     if j.get("batch") else "")
            print(f"    {jid:<10} {j.get('model', '?'):<12} "
                  f"L={j.get('L', '?'):<5} "
                  f"{j.get('status') or 'in-flight':<18}"
                  f"{batch}{req} {wait} {total}")


def report_fleet(events) -> None:
    """The distributed-fleet story (docs/SERVICE.md): membership
    joins/losses, batch fail-overs, and the result cache's
    hit/miss/publish ledger distilled from the (rank-merged) stream —
    the section an operator checks to answer "did the fleet lose a
    member, and did any accepted job go with it?" (the correct answer
    to the second half is always no)."""
    def kind_of(e):
        return e.get("kind") or e.get("event")

    joins = [e for e in events if kind_of(e) == "worker_join"]
    losses = [e for e in events if kind_of(e) == "worker_lost"]
    failovers = [e for e in events if kind_of(e) == "job_failover"]
    hits = [e for e in events if kind_of(e) == "cache_hit"]
    misses = [e for e in events if kind_of(e) == "cache_miss"]
    publishes = [e for e in events if kind_of(e) == "cache_publish"]
    if not (joins or losses or failovers or hits or misses
            or publishes):
        return
    print("== fleet ==")
    roles: dict = {}
    for e in joins:
        role = (e.get("attrs") or {}).get("role", "?")
        roles[role] = roles.get(role, 0) + 1
    role_s = " ".join(f"{r}={n}" for r, n in sorted(roles.items()))
    print(f"  members joined={len(joins)} ({role_s or '-'}) "
          f"lost={len(losses)} job failovers={len(failovers)}")
    for e in losses:
        attrs = e.get("attrs") or {}
        print(f"  lost {attrs.get('worker')}")
    for e in failovers:
        attrs = e.get("attrs") or {}
        print(f"  failover {attrs.get('job')} "
              f"(batch {attrs.get('batch')}) off dead worker "
              f"{attrs.get('worker')}")
    lookups = len(hits) + len(misses)
    rate = f"{100 * len(hits) / lookups:.1f}%" if lookups else "-"
    print(f"  cache: {len(hits)} hit / {len(misses)} miss "
          f"({rate} hit rate), {len(publishes)} publish(es)")
    for e in hits:
        attrs = e.get("attrs") or {}
        print(f"  hit {attrs.get('job')} <- "
              f"{str(attrs.get('digest'))[:12]} "
              f"(tenant {attrs.get('tenant')})")


def report_integrity(events) -> None:
    """The data-integrity story (docs/RESILIENCE.md): detected
    corruptions, replica failovers, and scrub audits distilled from
    the stream — the section an operator checks to answer "did this
    campaign ever serve or survive a corrupt byte?"."""
    def kind_of(e):
        return e.get("kind") or e.get("event")

    corruptions = [e for e in events if kind_of(e) == "corruption"]
    failovers = [e for e in events if kind_of(e) == "replica_failover"]
    scrubs = [e for e in events if kind_of(e) == "scrub"]
    injected = [
        e for e in events
        if kind_of(e) == "injected"
        and (e.get("attrs", e).get("fault")
             or e.get("attrs", e).get("kind"))
        in ("bitflip", "ckpt_corrupt")
    ]
    if not (corruptions or failovers or scrubs or injected):
        return
    audited = sum(
        (e.get("attrs", e).get("steps_audited") or 0) for e in scrubs
    )
    quarantined = sum(
        (e.get("attrs", e).get("corrupt") or 0) for e in scrubs
    )
    print("== integrity ==")
    print(f"  corruption events={len(corruptions)} "
          f"replica failovers={len(failovers)} "
          f"scrub audits={len(scrubs)} "
          f"(steps audited={audited}, quarantined={quarantined}) "
          f"injected faults={len(injected)}")
    for e in corruptions:
        attrs = e.get("attrs", e)
        where = attrs.get("path") or attrs.get("file") or ""
        step = e.get("step", attrs.get("step"))
        print(f"  corruption {'step ' + str(step) + ' ' if step is not None else ''}"
              f"{where}: {attrs.get('detail')}")
    for e in failovers:
        attrs = e.get("attrs", e)
        print(f"  failover {attrs.get('path')} -> {attrs.get('next')} "
              f"({attrs.get('detail')})")


def report_sdc(events) -> None:
    """The compute-path SDC story (resilience/sdc.py,
    docs/RESILIENCE.md "Silent data corruption"): how many redundant-
    compute screens ran, what they caught, which device got the blame,
    and whether anything was quarantined — the section an operator
    checks to answer "did any chip compute a wrong answer?"."""
    def kind_of(e):
        return e.get("kind") or e.get("event")

    def attrs_of(e):
        return e.get("attrs") or e

    checks = [e for e in events if kind_of(e) == "sdc_check"]
    mismatches = [e for e in events if kind_of(e) == "sdc_mismatch"]
    quarantines = [e for e in events
                   if kind_of(e) == "device_quarantined"]
    degraded = [e for e in events if kind_of(e) == "worker_degraded"]
    injected = [
        e for e in events
        if kind_of(e) == "injected"
        and (attrs_of(e).get("fault") or attrs_of(e).get("kind")) == "sdc"
    ]
    if not (checks or mismatches or quarantines or degraded or injected):
        return
    ok = sum(1 for e in checks if attrs_of(e).get("status") == "ok")
    replayed = sum(
        (attrs_of(e).get("replayed_steps") or 0) for e in checks
    )
    modes = sorted({attrs_of(e).get("mode") for e in checks
                    if attrs_of(e).get("mode")})
    print("== sdc ==")
    print(f"  screens={len(checks)} (ok={ok}, "
          f"steps replayed={replayed}"
          f"{', mode ' + '/'.join(modes) if modes else ''}) "
          f"mismatches={len(mismatches)} "
          f"quarantines={len(quarantines)} "
          f"injected faults={len(injected)}")
    for e in mismatches:
        a = attrs_of(e)
        member = a.get("member")
        print(f"  mismatch step {e.get('step', a.get('step'))} "
              f"({a.get('mode')}): device {a.get('device')}"
              f"{', member ' + str(member) if member is not None else ''}"
              f", last verified step {a.get('verified_step')}")
    for e in quarantines:
        a = attrs_of(e)
        print(f"  quarantined {a.get('device')} "
              f"at step {e.get('step', a.get('step'))}: "
              f"{a.get('reason')}")
    for e in degraded:
        a = attrs_of(e)
        print(f"  worker degraded: {a.get('reason')}")


def report_timeline(events, top: int) -> None:
    """The fault/recovery story, oldest first, with relative times —
    one chronological timeline; multi-process streams (rank-merged by
    the caller) get a per-record proc column so every line is
    attributed."""
    interesting = [e for e in events if e.get("kind") not in
                   ("output", "checkpoint", "numerics")]
    if not interesting:
        return
    procs = {e.get("proc") for e in events if e.get("proc") is not None}
    multi = len(procs) > 1
    t0 = interesting[0].get("ts") or 0
    print("== timeline ==")
    for e in interesting:
        attrs = e.get("attrs") or {}
        extra = ""
        if attrs.get("fault"):
            extra += f" fault={attrs['fault']}"
        if attrs.get("action"):
            extra += f" action={attrs['action']}"
        if attrs.get("error"):
            extra += f" error={attrs['error']}"
        if attrs.get("cache"):
            extra += f" cache={attrs['cache']}"
        if attrs.get("tripped"):
            extra += " " + ",".join(
                f"{k}={v:+.3f}" for k, v in attrs["tripped"].items()
            )
        if e.get("kind") == "executable":
            extra += (f" {attrs.get('name')} "
                      f"compile={_fmt_s(attrs.get('compile_s'))}"
                      f" cache={attrs.get('cache', '-')}")
        step = e.get("step")
        proc_col = f"p{e.get('proc', '?')} " if multi else ""
        print(f"  +{(e.get('ts') or t0) - t0:8.3f}s  {proc_col}"
              f"{e.get('kind', '?'):<20} "
              f"{'step ' + str(step) if step is not None else '':<10}"
              f"{extra}")


def report_slow_rounds(doc: dict, top: int) -> None:
    spans = [e for e in doc.get("traceEvents", [])
             if isinstance(e, dict) and e.get("ph") == "X"
             and e.get("name") in ("step_round", "compute", "compile")]
    if not spans:
        return
    spans.sort(key=lambda e: -e["dur"])
    print(f"== slowest rounds (top {top}) ==")
    for e in spans[:top]:
        step = (e.get("args") or {}).get("step")
        print(f"  {e['name']:<12} step={step!s:<8} "
              f"{e['dur'] / 1e3:10.3f}ms at t+{e['ts'] / 1e6:.3f}s")


def main() -> int:
    ap = argparse.ArgumentParser(
        description="render gray-scott observability artifacts"
    )
    ap.add_argument("--stats", help="GS_TPU_STATS summary JSON")
    ap.add_argument("--trace", help="GS_TRACE Chrome trace JSON")
    ap.add_argument("--events",
                    help="GS_EVENTS unified stream JSONL (multi-"
                    "process .rank<N> siblings are merged in "
                    "automatically)")
    ap.add_argument("--metrics",
                    help="GS_METRICS interval JSONL (.rank<N> "
                    "siblings merged, summarized per proc)")
    ap.add_argument("--check", action="store_true",
                    help="validate schemas only; no report")
    ap.add_argument("--top", type=int, default=5,
                    help="slowest rounds to list (default 5)")
    args = ap.parse_args()
    if not (args.stats or args.trace or args.events or args.metrics):
        ap.error("need at least one of --stats / --trace / --events "
                 "/ --metrics")
    if args.check:
        return check(args.trace, args.events, args.stats,
                     args.metrics)

    stats = None
    if args.stats:
        with open(args.stats, encoding="utf-8") as f:
            stats = json.load(f)
        report_stats(stats)
    if args.trace:
        with open(args.trace, encoding="utf-8") as f:
            doc = json.load(f)
        problems = validate_trace(doc)
        if problems:
            print(f"gs_report: warning — trace has "
                  f"{len(problems)} schema problem(s)", file=sys.stderr)
        report_slow_rounds(doc, args.top)
    if args.metrics:
        report_metrics_files(args.metrics)
    events = []
    if args.events:
        events = parse_events_multi(args.events)
    elif stats and stats.get("faults"):
        events = stats["faults"]
    if events:
        report_attempts(events)
        report_tenants(events)
        report_fleet(events)
        report_integrity(events)
        report_sdc(events)
        report_timeline(events, args.top)
    return 0


if __name__ == "__main__":
    sys.exit(main())
