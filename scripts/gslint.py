#!/usr/bin/env python3
"""gslint — the framework's contracts, statically enforced.

    python scripts/gslint.py [paths...]          # default: whole tree
    python scripts/gslint.py --json [paths...]   # stable tooling output
    python scripts/gslint.py --list              # pass catalog
    python scripts/gslint.py --select env-knobs,layering [paths...]

Runs without JAX (stdlib + the JAX-free ``grayscott_jl_tpu.lint``
package).  Exit code: 0 when no error-severity findings remain after
per-line suppressions and the (always-empty, committed) baseline at
``gslint-baseline.json``; 1 otherwise.  Warnings print but do not
fail.  See docs/ANALYSIS.md for the pass catalog, the suppression
syntax, and the ``--json`` schema.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from grayscott_jl_tpu import lint  # noqa: E402

#: Default lint surface: the package, the operator scripts, and the
#: bench entry point (mirrors the tier-1 self-check).
DEFAULT_TARGETS = ("grayscott_jl_tpu", "scripts", "bench.py")

BASELINE = os.path.join(REPO, "gslint-baseline.json")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="gslint",
        description="JAX-aware static analysis for grayscott_jl_tpu",
    )
    ap.add_argument(
        "paths", nargs="*", default=None,
        help=f"files/dirs to lint (default: {' '.join(DEFAULT_TARGETS)})",
    )
    ap.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit the stable gslint/1 JSON document to stdout",
    )
    ap.add_argument(
        "--list", action="store_true",
        help="list available passes and exit",
    )
    ap.add_argument(
        "--select", default="",
        help="comma-separated pass ids to run (default: all)",
    )
    ap.add_argument(
        "--baseline", default=BASELINE,
        help="baseline file of finding keys to ignore "
             "(committed empty by contract)",
    )
    ap.add_argument(
        "--root", default=REPO,
        help="repo root paths are resolved against (default: the "
             "checkout containing this script)",
    )
    args = ap.parse_args(argv)

    if args.list:
        for pass_id in sorted(lint.PASSES):
            doc = (sys.modules[lint.PASSES[pass_id].__module__]
                   .__doc__ or "").strip().splitlines()
            print(f"{pass_id:<14} {doc[0] if doc else ''}")
        return 0

    targets = args.paths or list(DEFAULT_TARGETS)
    select = [s.strip() for s in args.select.split(",") if s.strip()]
    baseline = []
    if args.baseline and os.path.isfile(args.baseline):
        baseline = lint.load_baseline(args.baseline)

    findings = lint.run_lint(
        args.root, targets, select=select or None, baseline=baseline
    )
    errors = [f for f in findings if f.severity == "error"]
    if args.as_json:
        print(json.dumps(
            lint.findings_to_json(findings, args.root, targets),
            indent=2, sort_keys=True,
        ))
    else:
        for f in findings:
            print(f.render())
        n_warn = len(findings) - len(errors)
        print(
            f"gslint: {len(errors)} error(s), {n_warn} warning(s) "
            f"over {len(targets)} target(s)"
        )
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
